"""Stall-forensics plane: introspection contract, sentinel, mpidiag
blame analysis, abort-path trace export, era timeout detail, mpitop
stall column, and the two procmode proofs.

The introspection-contract test is the satellite guard: every module
registering a ``debug_state()`` provider must return JSON-serializable,
bounded output under an active workload — a new subsystem can't
silently ship broken dumps.
"""

import json
import os
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from ompi_tpu import COMM_SELF, COMM_WORLD  # noqa: E402
from ompi_tpu.core.errors import MPIError, ERR_PENDING  # noqa: E402
from ompi_tpu.mca.var import all_pvars, all_vars, get_var, set_var  # noqa: E402
from ompi_tpu.runtime import forensics as fx  # noqa: E402
from ompi_tpu.runtime import trace as _trace  # noqa: E402
from ompi_tpu.runtime.progress import progress_until  # noqa: E402

import mpidiag  # noqa: E402
import mpitop  # noqa: E402


def subprocess_env():
    env = os.environ.copy()
    env.pop("OMPI_TPU_RANK", None)
    pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
          if p and not any("axon" in part for part in p.split(os.sep))]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + pp)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.environ.get("OMPI_TPU_TEST_JAX_CACHE",
                                  "/tmp/ompi_tpu_jax_cache"))
    return env


def run_mpi(np_, script, *args, timeout=180, mca=(), env_extra=()):
    cmd = [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", str(np_)]
    for k, v in mca:
        cmd += ["--mca", k, str(v)]
    cmd += [script, *args]
    env = subprocess_env()
    env.update(dict(env_extra))
    return subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          timeout=timeout, env=env)


@pytest.fixture
def restore_vars():
    saved = {}

    def save(fw, name):
        saved[(fw, name)] = get_var(fw, name)

    yield save
    for (fw, name), v in saved.items():
        set_var(fw, name, v)
    fx.reset_for_testing()


# -------------------------------------------------- introspection contract
def test_every_provider_json_serializable_under_workload():
    """The contract itself: with real traffic in flight AND pathological
    queue depth, every registered provider returns JSON-serializable
    output with no tracebacks and no unbounded fields."""
    x = np.ones(256, np.float32)
    out = np.zeros(256, np.float32)
    COMM_SELF.Sendrecv(x, 0, 7, out, 0, 7)
    # pathological pending state: far more posted receives than CAP
    pend = [COMM_WORLD.Irecv(np.zeros(4), 0, 1000 + i)
            for i in range(3 * fx.CAP)]
    try:
        state = fx.debug_state()
        json.dumps(state)  # no TypeError = serializable
        assert "pml" in state and "runtime.progress" in state
        pml = state["pml"]
        assert "error" not in pml
        posted = pml["matching"]["posted"]
        assert len(posted) <= fx.CAP  # bounded
        assert pml["matching"]["posted_omitted"] >= 2 * fx.CAP
        assert pml["matching"]["n_posted"] >= 3 * fx.CAP
    finally:
        for r in pend:
            assert COMM_WORLD.pml.cancel_recv(r)
            r.Wait()


def test_broken_provider_isolated_not_fatal():
    def bad():
        raise RuntimeError("boom")

    fx.register_provider("test.broken", bad)
    try:
        state = fx.debug_state()
        assert state["test.broken"]["error"].startswith("RuntimeError")
        json.dumps(state)
    finally:
        with fx._lock:
            fx._providers.pop("test.broken", None)


def test_provider_rebind_latest_wins():
    fx.register_provider("test.rebind", lambda: {"v": 1})
    fx.register_provider("test.rebind", lambda: {"v": 2})
    try:
        assert fx.debug_state()["test.rebind"] == {"v": 2}
    finally:
        with fx._lock:
            fx._providers.pop("test.rebind", None)


def test_none_provider_skipped():
    fx.register_provider("test.none", lambda: None)
    try:
        assert "test.none" not in fx.debug_state()
    finally:
        with fx._lock:
            fx._providers.pop("test.none", None)


def test_clip_bounds():
    assert fx.clip(list(range(200))) == list(range(fx.CAP))
    assert fx.clip([]) == []
    assert fx.clip(iter(range(200))) == list(range(fx.CAP))


def test_ob1_clip_keeps_oldest_and_counts_omitted():
    """CAP clipping must keep the OLDEST entries (the blame walk keys
    on the oldest blocked recv) and say how many it dropped — dict
    insertion order silently dropped the oldest past CAP (review)."""
    pml = COMM_WORLD.pml
    now = time.monotonic()
    fakes = {}
    for i in range(fx.CAP + 8):
        st = types.SimpleNamespace(source=0, _nbytes=4)
        # inserted newest-first: insertion-order clipping would keep
        # exactly the WRONG end of the queue
        fakes[10_000_000 + i] = types.SimpleNamespace(
            tag=i, cid=0, status=st, _recv_bytes=0,
            _fx_born=now - i)  # entry i is i seconds old
    pml._active_recvs.update(fakes)
    try:
        d = pml.debug_state()
        active = d["active_recvs"]
        assert len(active) <= fx.CAP
        assert d["active_recvs_omitted"] >= 8
        got = {a["tag"] for a in active if a["msgid"] >= 10_000_000}
        # the CAP oldest fakes survive; the 8 newest are the omitted
        assert got == set(range(8, fx.CAP + 8))
        assert "flowing_sends_omitted" in d
    finally:
        for m in fakes:
            pml._active_recvs.pop(m, None)


def test_sched_and_era_providers_count_omitted():
    """Every clipped provider list carries its omitted count — the
    forensics contract the CAP doc promises (review finding: sched
    blocking/nbc and era rounds truncated silently)."""
    from ompi_tpu.coll import sched as _sched
    from ompi_tpu.ft.era import EraEngine

    now = time.monotonic()
    keys = [f"fx-test-{i}" for i in range(fx.CAP + 3)]
    with _sched._fx_lock:
        for i, k in enumerate(keys):
            _sched._live_blocking[k] = {"born": now, "tag": i}
    try:
        d = _sched._fx_debug_state()
        assert len(d["blocking"]) == fx.CAP
        assert d["blocking_omitted"] >= 3
        assert d["nbc_inflight_omitted"] == 0
    finally:
        with _sched._fx_lock:
            for k in keys:
                _sched._live_blocking.pop(k, None)

    eng = EraEngine(_DummyPml())
    for seq in range(fx.CAP + 5):
        eng._state(55, seq)
    d = eng.debug_state()
    assert len(d["rounds"]) == fx.CAP
    assert d["rounds_omitted"] == 5


# ----------------------------------------------------------- the sentinel
def test_sentinel_latches_dumps_and_rearms(tmp_path, restore_vars):
    restore_vars("metrics", "dir")
    restore_vars("forensics", "enable")
    restore_vars("forensics", "stall_threshold_ms")
    set_var("metrics", "dir", str(tmp_path))
    set_var("forensics", "stall_threshold_ms", 60.0)
    set_var("forensics", "enable", True)
    fx.arm_sentinel()
    trips0 = fx._trips[0]
    stalled = COMM_WORLD.Irecv(np.zeros(4), 0, 4242)  # never matched
    try:
        assert progress_until(lambda: fx._sentinel.latched, timeout=8.0)
        assert fx._trips[0] == trips0 + 1
        assert int(all_pvars()["forensics_stall_latched"].value) == 1
        path = tmp_path / "stall-rank0.json"
        assert path.exists()
        doc = json.loads(path.read_text())
        assert "stall-sentinel" in doc["reason"]
        assert doc["stall"]["latched"]
        posted = doc["subsystems"]["pml"]["matching"]["posted"]
        assert any(p["tag"] == 4242 for p in posted)
    finally:
        assert COMM_WORLD.pml.cancel_recv(stalled)
        stalled.Wait()
    # the cancel completion re-arms the latch
    assert progress_until(lambda: not fx._sentinel.latched, timeout=8.0)
    assert int(all_pvars()["forensics_stall_latched"].value) == 0


def test_sentinel_idle_is_not_a_stall(restore_vars, tmp_path):
    """No pending work => no latch, however long nothing completes."""
    restore_vars("metrics", "dir")
    restore_vars("forensics", "enable")
    restore_vars("forensics", "stall_threshold_ms")
    set_var("metrics", "dir", str(tmp_path))
    set_var("forensics", "stall_threshold_ms", 40.0)
    set_var("forensics", "enable", True)
    fx.arm_sentinel()
    trips0 = fx._trips[0]
    deadline = time.monotonic() + 0.5
    while time.monotonic() < deadline:
        progress_until(lambda: False, timeout=0.05)
    assert fx._trips[0] == trips0
    assert not fx._sentinel.latched


def test_fresh_work_after_idle_is_not_an_instant_stall(tmp_path,
                                                       restore_vars):
    """The idle clock must stay fresh WHILE idle: after a long quiet
    stretch, newly-posted work gets the full threshold before a latch
    — a threshold-stale clock latched ~immediately on the first
    operation after idling (4th review pass)."""
    restore_vars("metrics", "dir")
    restore_vars("forensics", "enable")
    restore_vars("forensics", "stall_threshold_ms")
    set_var("metrics", "dir", str(tmp_path))
    set_var("forensics", "stall_threshold_ms", 400.0)
    set_var("forensics", "enable", True)
    fx.reset_for_testing()
    fx.arm_sentinel()
    trips0 = fx._trips[0]
    # idle well past the threshold, with the sentinel polling
    deadline = time.monotonic() + 1.0
    while time.monotonic() < deadline:
        progress_until(lambda: False, timeout=0.05)
    stalled = COMM_WORLD.Irecv(np.zeros(4), 0, 4243)
    try:
        # a quarter-threshold later: must NOT have latched yet
        deadline = time.monotonic() + 0.1
        while time.monotonic() < deadline:
            progress_until(lambda: False, timeout=0.02)
        assert not fx._sentinel.latched
        assert fx._trips[0] == trips0
        # ... but the genuine stall still latches after the threshold
        assert progress_until(lambda: fx._sentinel.latched, timeout=8.0)
    finally:
        assert COMM_WORLD.pml.cancel_recv(stalled)
        stalled.Wait()


def test_reenable_after_disabled_stretch_is_not_an_instant_stall(
        tmp_path, restore_vars):
    """forensics_enable 1 -> 0 -> 1 through a cvar write on a live job:
    while disabled the completion tick is unbound, so the idle clock
    goes stale by the whole window — the rebind hook must reset it or
    the first poll that finds any pending work latches a healthy job
    instantly (5th review pass)."""
    restore_vars("metrics", "dir")
    restore_vars("forensics", "enable")
    restore_vars("forensics", "stall_threshold_ms")
    set_var("metrics", "dir", str(tmp_path))
    set_var("forensics", "stall_threshold_ms", 400.0)
    set_var("forensics", "enable", True)
    fx.reset_for_testing()
    fx.arm_sentinel()
    trips0 = fx._trips[0]
    set_var("forensics", "enable", False)
    time.sleep(1.0)  # disabled stretch well past the threshold
    set_var("forensics", "enable", True)
    stalled = COMM_WORLD.Irecv(np.zeros(4), 0, 4244)
    try:
        # a quarter-threshold later: must NOT have latched yet
        deadline = time.monotonic() + 0.1
        while time.monotonic() < deadline:
            progress_until(lambda: False, timeout=0.02)
        assert not fx._sentinel.latched
        assert fx._trips[0] == trips0
        # ... but the genuine stall still latches after the threshold
        assert progress_until(lambda: fx._sentinel.latched, timeout=8.0)
    finally:
        assert COMM_WORLD.pml.cancel_recv(stalled)
        stalled.Wait()


def test_undriven_poll_gap_is_idle_not_stall(tmp_path, restore_vars):
    """With no progress driver (runtime_progress_thread 0) nothing
    polls while the app computes outside MPI: the clock goes
    threshold-stale UNOBSERVED, and the first poll after fresh work is
    posted must treat the gap as idle time, not latch instantly — the
    sentinel can only measure time it was watching (review)."""
    restore_vars("metrics", "dir")
    restore_vars("forensics", "enable")
    restore_vars("forensics", "stall_threshold_ms")
    set_var("metrics", "dir", str(tmp_path))
    set_var("forensics", "stall_threshold_ms", 400.0)
    set_var("forensics", "enable", True)
    fx.reset_for_testing()
    fx.arm_sentinel()
    trips0 = fx._trips[0]
    s = fx._sentinel
    s.poll()  # one watched poll, then an undriven stretch
    # simulate a 10s unobserved compute gap exactly as elapsed time
    # would leave the clocks: nothing polled, nothing completed
    with s._slock:
        s._last_change -= 10.0
        s._last_poll -= 10.0
        s._next_probe = 0.0
    stalled = COMM_WORLD.Irecv(np.zeros(4), 0, 4245)
    try:
        s.poll()  # first poll after the gap: idle, not a latch
        assert not s.latched
        assert fx._trips[0] == trips0
        # ...but a genuine stall still latches once it is WATCHED
        # past the threshold
        assert progress_until(lambda: s.latched, timeout=8.0)
    finally:
        assert COMM_WORLD.pml.cancel_recv(stalled)
        stalled.Wait()


def test_runtime_cvar_flip_arms_the_whole_plane(restore_vars,
                                                monkeypatch):
    """Flipping forensics_enable through a cvar write on a live job
    must arm the sentinel + SIGUSR1, not just the completion tick."""
    restore_vars("forensics", "enable")
    set_var("forensics", "enable", False)
    armed = []
    monkeypatch.setattr(fx, "arm_sentinel", lambda: armed.append("s"))
    monkeypatch.setattr(fx, "install_sigusr1",
                        lambda: armed.append("sig"))
    set_var("forensics", "enable", True)
    assert armed == ["s", "sig"]
    from ompi_tpu.core import request as _request

    assert _request._fx_note is fx.note_completion
    set_var("forensics", "enable", False)
    assert _request._fx_note is None


def test_completion_during_pending_probe_blocks_the_latch(
        restore_vars, tmp_path, monkeypatch):
    """A request that completes while poll() is inside the pending
    probes (which take contended subsystem locks — a wide window) must
    veto the latch: the entry snapshot is stale there and _last_comp
    only advances in the fold, so the guard must re-read the live
    counter (5th review pass)."""
    restore_vars("metrics", "dir")
    restore_vars("forensics", "enable")
    restore_vars("forensics", "stall_threshold_ms")
    set_var("metrics", "dir", str(tmp_path))
    set_var("forensics", "stall_threshold_ms", 40.0)
    set_var("forensics", "enable", True)
    fx.reset_for_testing()
    fx.arm_sentinel()
    trips0 = fx._trips[0]
    with fx._sentinel._slock:
        fx._sentinel._last_comp = fx._completions[0]
        fx._sentinel._last_change = time.monotonic() - 99.0
        fx._sentinel._next_probe = 0.0
        fx._sentinel.latched = False

    def pending_and_tick():
        fx._completions[0] += 1  # a request completes mid-probe
        return True

    monkeypatch.setattr(fx, "_work_pending", pending_and_tick)
    assert fx._sentinel.poll() == 0
    assert not fx._sentinel.latched
    assert fx._trips[0] == trips0
    # the next poll folds the tick: clock fresh, still no latch
    monkeypatch.setattr(fx, "_work_pending", lambda: True)
    assert fx._sentinel.poll() == 0
    assert not fx._sentinel.latched


def test_runtime_disable_clears_the_latch(restore_vars, tmp_path):
    """Silencing the plane (enable 1 -> 0) on a latched sentinel must
    clear the verdict: the completion tick is unbound, so nothing else
    ever could — the stall pvar and mpitop cell would otherwise report
    a latched stall with an unboundedly climbing age on a healthy job
    for the rest of the run (5th review pass)."""
    restore_vars("metrics", "dir")
    restore_vars("forensics", "enable")
    set_var("metrics", "dir", str(tmp_path))
    set_var("forensics", "enable", True)
    fx.reset_for_testing()
    fx.arm_sentinel()
    with fx._sentinel._slock:
        fx._sentinel.latched = True
        fx._sentinel._last_comp = fx._completions[0]
        fx._sentinel._last_change = time.monotonic() - 99.0
    set_var("forensics", "enable", False)
    assert not fx._sentinel.latched
    assert int(all_pvars()["forensics_stall_latched"].value) == 0
    assert fx._sentinel.age() == 0.0
    # re-enable re-arms with a fresh clock
    set_var("forensics", "enable", True)
    assert fx._sentinel.armed
    assert not fx._sentinel.latched
    assert fx._sentinel.age() < 1.0


def test_legacy_wire_paths_stamp_rx_tx_evidence(restore_vars):
    """btl_tcp_copy_mode=1 (the kept A/B baseline) must stamp
    last_rx/last_tx like the vectored paths do — a dump on a moving
    legacy link otherwise shows null wire-liveness, indistinguishable
    from a silent one (5th review pass)."""
    from ompi_tpu.btl.tcp import TcpBtl
    from ompi_tpu.pml.base import pack_header

    restore_vars("forensics", "enable")
    restore_vars("btl_tcp", "copy_mode")
    set_var("forensics", "enable", True)
    set_var("btl_tcp", "copy_mode", 1)
    got = []
    a = TcpBtl(lambda h, p: got.append(bytes(p)), my_rank=0)
    b = TcpBtl(lambda h, p: got.append(bytes(p)), my_rank=1)
    try:
        a.set_peers({1: f"127.0.0.1:{b.port}"})
        b.set_peers({0: f"127.0.0.1:{a.port}"})
        hdr = pack_header(1, 0, 0, 5, 1, 5, 0, 0)
        a.send(1, hdr, np.frombuffer(b"hello", np.uint8))
        deadline = time.monotonic() + 10
        while not got and time.monotonic() < deadline:
            a.progress()
            b.progress()
        assert got == [b"hello"]
        assert any(c["last_tx_age_s"] is not None
                   for c in a.debug_state()["conns"])
        assert any(c["last_rx_age_s"] is not None
                   for c in b.debug_state()["conns"])
        # torn rx span (parser mid-compaction on the progress thread):
        # the dump must clamp, never record negative evidence
        conn = next(iter(b.conns.values()))
        r0, r1 = conn.rstart, conn.rend
        conn.rstart, conn.rend = 5000, 100
        try:
            assert all(c["rx_partial_bytes"] >= 0
                       for c in b.debug_state()["conns"])
        finally:
            conn.rstart, conn.rend = r0, r1
    finally:
        a.finalize()
        b.finalize()


def test_watchdog_dump_captures_pre_conversion_evidence(
        tmp_path, restore_vars):
    """The watchdog trigger must dump BEFORE _fail_requests pops the
    stale entries — afterwards the protocol state it exists to capture
    is gone (4th review pass)."""
    import threading as _threading

    from ompi_tpu.ft import detector as _det
    from ompi_tpu.pml.base import SendRequest
    from ompi_tpu.pml.ob1 import Ob1Pml

    restore_vars("metrics", "dir")
    restore_vars("forensics", "enable")
    restore_vars("pml", "peer_timeout")
    set_var("metrics", "dir", str(tmp_path))
    set_var("forensics", "enable", True)
    set_var("pml", "peer_timeout", 0.5)
    fx.reset_for_testing()  # clear the trigger rate limiter
    world_pml = COMM_WORLD.pml
    pml = Ob1Pml(my_rank=0)
    req = SendRequest(dst=3, tag=9, cid=0, nbytes=4096)
    req._pump_lock = _threading.RLock()
    req._wd_last = time.monotonic() - 10.0
    pml._pending_sends[77] = req
    pml._wd_next = 0.0
    try:
        assert pml._watchdog_poll() == 1
        assert req.is_complete  # the conversion still happened
        doc = json.loads((tmp_path / "stall-rank0.json").read_text())
        assert "pml-watchdog" in doc["reason"]
        pend = doc["subsystems"]["pml"]["pending_sends"]
        assert any(e["msgid"] == 77 and e["dst"] == 3
                   and e["stage"] == "rts-unanswered" for e in pend), \
            f"pre-conversion evidence missing: {pend}"
    finally:
        with _det._failed_lock:  # undo the watchdog's mark_failed(3)
            _det._failed.discard(3)
        # rebind the world pml's provider (the test pml took the slot)
        fx.register_provider(
            "pml", lambda: world_pml.debug_state())
        fx.register_pending_probe(
            "pml", lambda: (world_pml.engine.n_posted
                            + len(world_pml._pending_sends)
                            + len(world_pml._active_recvs)
                            + len(world_pml._flowing)))


def test_dump_state_verb_works_with_plane_disabled(tmp_path,
                                                   restore_vars):
    restore_vars("metrics", "dir")
    set_var("metrics", "dir", str(tmp_path))
    assert not fx.enabled()
    path = COMM_SELF.Dump_state(reason="unit")
    assert path == str(tmp_path / "stall-rank0.json")
    doc = json.loads(open(path).read())
    assert doc["reason"] == "unit"
    assert "pml" in doc["subsystems"]


def test_dump_rate_limit(tmp_path, restore_vars):
    restore_vars("metrics", "dir")
    set_var("metrics", "dir", str(tmp_path))
    assert fx.dump(reason="first") is not None
    assert fx.dump(reason="second", min_interval=30.0) is None
    assert fx.dump(reason="third") is not None  # unlimited path


def test_failed_dump_does_not_suppress_rate_limited_retry(
        tmp_path, restore_vars, monkeypatch):
    """A dump whose write fails (disk-full blip) must not advance the
    rate-limit stamp: the retry within min_interval is exactly the one
    that would have succeeded (5th review pass)."""
    from ompi_tpu.utils import fsio

    restore_vars("metrics", "dir")
    set_var("metrics", "dir", str(tmp_path))
    fx._last_dump_ts[0] = 0.0
    real = fsio.atomic_write_json
    fail = [True]

    def flaky(path, doc, **kw):
        if fail[0]:
            raise OSError("disk full")
        return real(path, doc, **kw)

    monkeypatch.setattr(fsio, "atomic_write_json", flaky)
    assert fx.dump(reason="failed", min_interval=30.0) is None
    fail[0] = False
    # the failed attempt must not have stamped: this retry lands
    assert fx.dump(reason="retry", min_interval=30.0) is not None
    doc = json.loads((tmp_path / "stall-rank0.json").read_text())
    assert doc["reason"] == "retry"
    # ... and the SUCCESS did stamp: an immediate third is suppressed
    assert fx.dump(reason="third", min_interval=30.0) is None


def test_trigger_requests_peers_even_when_local_dump_fails(
        tmp_path, restore_vars, monkeypatch):
    """The local-only fallback runs BOTH ways: a rank whose own disk is
    unwritable must still harvest every peer's evidence."""
    restore_vars("metrics", "dir")
    set_var("metrics", "dir", str(tmp_path))
    fx.reset_for_testing()
    asked = []
    monkeypatch.setattr(fx, "_request_all_peer_dumps",
                        lambda reason: asked.append(reason))
    monkeypatch.setattr(fx, "dump", lambda **kw: None)  # write fails
    assert fx.trigger("era-timeout: unit") is None
    assert asked == ["era-timeout: unit"]  # peers asked anyway
    # rate limit: an immediate re-trigger skips BOTH (peers were just
    # asked), instead of flooding per watchdog sweep
    assert fx.trigger("era-timeout: unit again") is None
    assert len(asked) == 1


def test_system_plane_completions_do_not_tick():
    """Heartbeats (every 200ms under ft_enable), era chatter, and the
    plane's own dump requests are system-plane sends — if their
    completions counted, an FT job's sentinel could never see a quiet
    period and the era-stall soak class would never latch (found by
    driving a real 2-rank era stall under ft_enable)."""

    class _Req:
        def __init__(self, tag):
            self.tag = tag

    base = fx._completions[0]
    fx.note_completion(_Req(-4243))   # heartbeat
    fx.note_completion(_Req(-4244))   # era
    fx.note_completion(_Req(fx.FORENSICS_TAG))  # our own dump request
    assert fx._completions[0] == base
    fx.note_completion(_Req(7))       # user traffic ticks
    fx.note_completion(None)          # tagless (coll/nbc) ticks
    assert fx._completions[0] == base + 2


def test_atomic_write_json_cleans_up_failed_tmp(tmp_path):
    from ompi_tpu.utils.fsio import atomic_write_json

    p = tmp_path / "out.json"
    assert atomic_write_json(str(p), {"a": 1}) == str(p)
    assert json.loads(p.read_text()) == {"a": 1}

    class Unserializable:
        pass

    with pytest.raises(TypeError):
        atomic_write_json(str(p), {"a": Unserializable()})
    # the failed write neither corrupted the file nor stranded a tmp
    assert json.loads(p.read_text()) == {"a": 1}
    assert os.listdir(tmp_path) == ["out.json"]


# ------------------------------------------------------------ mpidiag
def _dump(rank, pml=None, tcp=None, latched=False, reason="x"):
    return {"schema": 1, "rank": rank, "seq": 1, "reason": reason,
            "ts_ns": 0, "wall_time": 0.0,
            "stall": {"latched": latched,
                      "since_last_completion_s": 1.0},
            "subsystems": {"pml": pml or {}, "btl.tcp": tcp or {}}}


def test_mpidiag_blames_dropped_frame_edge():
    dumps = {
        1: _dump(1, latched=True, reason="stall-sentinel", pml={
            "matching": {"posted": [
                {"cid": 0, "src": 0, "tag": 7, "n": 1,
                 "oldest_pseq": 0, "oldest_age_s": 3.2}]},
            "expect_seq": {},
        }),
        0: _dump(0, pml={"matching": {"posted": []},
                         "seq_to": {"1:0": 4}}),
    }
    report = mpidiag.analyze(dumps)
    assert len(report["blames"]) == 1
    b = report["blames"][0]
    assert "rank 1 blocked on MATCH tag 7 cid 0 from rank 0" in b
    assert "stamped seq 4 on the normal plane" in b
    assert "expects 1" in b
    assert not report["cycles"]


def test_mpidiag_blames_rts_and_queue_position():
    dumps = {
        2: _dump(2, latched=True, pml={
            "matching": {"posted": [
                {"cid": 1, "src": 0, "tag": 9, "n": 1,
                 "oldest_pseq": 0, "oldest_age_s": 8.0}]},
        }),
        0: _dump(0, pml={
            "matching": {"posted": []},
            "pending_sends": [{"msgid": 3, "dst": 2, "tag": 9,
                               "cid": 1, "nbytes": 1 << 20,
                               "stage": "rts-unanswered",
                               "age_s": 8.0}],
        }, tcp={"conns": [
            {"peer": 2, "state": "established",
             "shaped_queues": {"bulk": {"frames": 3,
                                        "bytes": 48_000_000,
                                        "oldest_age_s": 8.0}}}]}),
    }
    report = mpidiag.analyze(dumps)
    b = report["blames"][0]
    assert "rank 2 blocked on MATCH tag 9 cid 1 from rank 0" in b
    assert "RTS" in b and "unanswered" in b
    assert "BULK queue" in b and "48.0MB" in b


def test_mpidiag_one_directional_wire_detail_renders_cleanly():
    """tx stamped but rx never (the seeded drop edge before any
    reverse traffic) must not render 'last rx never ago' (5th review
    pass)."""
    dumps = {
        1: _dump(1, latched=True, pml={
            "matching": {"posted": [
                {"cid": 0, "src": 0, "tag": 7, "n": 1,
                 "oldest_pseq": 0, "oldest_age_s": 3.2}]},
        }),
        0: _dump(0, pml={"matching": {"posted": []}},
                 tcp={"conns": [{"peer": 1, "state": "established",
                                 "last_tx_age_s": 0.4,
                                 "last_rx_age_s": None}]}),
    }
    b = mpidiag.analyze(dumps)["blames"][0]
    assert "last tx 0.4s ago, last rx never" in b
    assert "never ago" not in b


def test_mpidiag_detects_cycle():
    def side(rank, peer, latched=True):
        return _dump(rank, latched=latched, pml={
            "matching": {"posted": [
                {"cid": 0, "src": peer, "tag": 5, "n": 1,
                 "oldest_pseq": 0, "oldest_age_s": 2.0}]},
        })

    report = mpidiag.analyze({0: side(0, 1), 1: side(1, 0)})
    assert report["cycles"] == ["0 -> 1 -> 0"]
    assert "BLAME-CYCLE" in mpidiag.render(report)
    # healthy on-demand snapshots of a routine ring exchange show the
    # same edge shape (dumps are never simultaneous) — with no rank
    # stalled that must NOT read as a deadlock (4th review pass)
    healthy = mpidiag.analyze({0: side(0, 1, latched=False),
                               1: side(1, 0, latched=False)})
    assert not healthy["cycles"] and not healthy["blames"]
    assert "no stalled rank" in mpidiag.render(healthy)


def test_mpidiag_blames_auto_trigger_reasons():
    """Auto-trigger dumps (era timeout, watchdog, sanitizer deadlock)
    carry no sentinel latch — their reasons alone must select them for
    blame, or the era show_help's 'run mpidiag' advice prints a
    healthy verdict for 6 of the 8 motivating soak failures."""
    for reason in ("era-timeout: round 3 cid 0 waiting on coordinator",
                   "pml-watchdog: peer(s) [0] silent > 2.0s",
                   "sanitizer-deadlock: cycle 0 -> 1 -> 0"):
        dumps = {
            1: _dump(1, reason=reason, pml={
                "matching": {"posted": [
                    {"cid": 0, "src": 0, "tag": 7, "n": 1,
                     "oldest_pseq": 0, "oldest_age_s": 3.0}]}}),
            0: _dump(0, reason=f"peer-request: {reason} on rank 1",
                     pml={"matching": {"posted": []},
                          "seq_to": {"1:0": 2}}),
        }
        report = mpidiag.analyze(dumps)
        assert report["blames"], f"no blame for reason {reason!r}"
        assert "rank 1 blocked on MATCH tag 7" in report["blames"][0]


def test_mpidiag_era_vote_edges_skip_known_failed_voters():
    """era's phase-1 predicate is contribution-OR-DEATH: a known-failed
    voter is satisfied, not blocking. The coordinator's ERA-VOTE edges
    must skip dead members or the tie-break blames a dead rank with 'no
    dump' while the live stalled voter goes unreported (review)."""
    dump = _dump(1, latched=True, reason="stall-sentinel")
    dump["subsystems"]["ft.era"] = {"rounds": [{
        "cid": 0, "round": 3, "members": [0, 1, 2],
        "contribs": [1], "votes_outstanding": [0, 2],
        "decision": False, "in_progress": True, "age_s": 4.0}]}
    dump["subsystems"]["ft.detector"] = {"known_failed": [0]}
    edges = mpidiag.blocked_edges(1, dump)
    era = [e for e in edges if e.kind == "ERA-VOTE"]
    assert [e.peer for e in era] == [2]  # dead rank 0 skipped
    # and the blame walk follows the live voter's edge
    report = mpidiag.analyze({1: dump})
    assert "waiting on rank 2's vote" in report["blames"][0]


def test_mpidiag_mixed_latched_and_trigger_both_blamed():
    """A mixed stall — one rank sentinel-latched, another dumped by an
    auto trigger — must blame BOTH; the trigger scan used to run only
    when no rank latched (review finding), dropping the era rank's
    edge from exactly the mixed verdict the soak produces."""
    def blocked(rank, peer, **kw):
        return _dump(rank, pml={
            "matching": {"posted": [
                {"cid": 0, "src": peer, "tag": 7, "n": 1,
                 "oldest_pseq": 0, "oldest_age_s": 3.0}]}}, **kw)

    dumps = {
        0: blocked(0, 2, latched=True, reason="stall-sentinel"),
        2: blocked(2, 1, reason="era-timeout: round 3 cid 0"),
        1: _dump(1, reason="peer-request: stall-sentinel on rank 0",
                 pml={"matching": {"posted": []}}),
    }
    report = mpidiag.analyze(dumps)
    blamed = " ".join(report["blames"])
    assert "rank 0 blocked on MATCH tag 7 cid 0 from rank 2" in blamed
    assert "rank 2 blocked on MATCH tag 7 cid 0 from rank 1" in blamed
    # the healthy peer-request rank is still never blamed
    assert "rank 1 blocked" not in blamed


def test_mpidiag_offsets_shift_ages_onto_one_timeline():
    """--offsets must actually ALIGN ages (review finding: they were
    echoed into summaries and never applied): with rank 0's dump taken
    2s after rank 1's, rank 1's ages grow by the skew so both sides
    compare as of one instant; without offsets nothing moves."""
    def dumps():
        d = {
            1: _dump(1, latched=True, pml={
                "matching": {"posted": [
                    {"cid": 0, "src": 0, "tag": 7, "n": 1,
                     "oldest_pseq": 0, "oldest_age_s": 3.0}]}}),
            0: _dump(0, pml={"matching": {"posted": []},
                             "seq_to": {"1:0": 4}}),
        }
        d[1]["ts_ns"] = 0
        d[0]["ts_ns"] = int(2e9)  # dumped 2s later on the same clock
        return d

    plain = mpidiag.analyze(dumps())
    assert "(3.0s)" in plain["blames"][0]
    assert plain["ranks"][1]["dump_skew_s"] == 0.0

    aligned = mpidiag.analyze(dumps(), offsets={0: 0.0, 1: 0.0})
    assert "(5.0s)" in aligned["blames"][0]  # 3.0 + 2s dump skew
    assert aligned["ranks"][1]["dump_skew_s"] == 2.0
    assert aligned["ranks"][0]["dump_skew_s"] == 0.0
    assert aligned["ranks"][1]["since_last_completion_s"] == 3.0

    # a real clock offset folds in per the trace_merge convention
    # (ts0 = ts_r - offset_r): rank 0's clock reads 2s AHEAD, so the
    # dumps were actually simultaneous and nothing shifts
    sync = mpidiag.analyze(dumps(), offsets={0: 2.0, 1: 0.0})
    assert "(3.0s)" in sync["blames"][0]
    assert sync["ranks"][1]["dump_skew_s"] == 0.0


def test_era_agreement_counts_as_pending_work():
    """An in-progress agreement posts no pml requests — the era pending
    probe is what keeps the sentinel from classifying an era stall as
    idle. The probe counts entered-but-not-exited rounds only."""
    from ompi_tpu.ft.era import EraEngine, _AgreeState

    eng = EraEngine(_DummyPml())
    probe = fx._pending_probes["ft.era"]
    base = probe()
    st = eng._state(55, 0)
    with st.lock:
        st.members = [0, 1]
    assert probe() == base + 1  # entered, not exited
    st.done = True
    assert probe() == base     # exited (return OR raise)
    # handler-created states (members unknown) never count
    eng._state(55, 1)
    assert probe() == base


def _era_round(cid, rnd, members, contribs, outstanding,
               in_progress=True, decision=False):
    return {"cid": cid, "round": rnd, "members": members,
            "contribs": contribs, "votes_outstanding": outstanding,
            "in_progress": in_progress, "decision": decision,
            "age_s": 5.0}


def test_mpidiag_blames_era_stall_without_pml_edges():
    """The era-stall class (6 of 8 soak failures): agreement waits ride
    system handlers and post NO pml requests — the blame walk must
    follow the ft.era rounds, not declare the job healthy."""
    dumps = {
        0: _dump(0, latched=True, reason="stall-sentinel"),
        1: _dump(1, latched=True, reason="stall-sentinel"),
    }
    # rank 0 coordinates round 2 on cid 3, missing rank 1's vote;
    # rank 1 never entered the round (stuck above the agreement)
    dumps[0]["subsystems"]["ft.era"] = {"rounds": [
        _era_round(3, 2, [0, 1], [0], [1])]}
    dumps[1]["subsystems"]["ft.era"] = {"rounds": []}
    report = mpidiag.analyze(dumps)
    b = [x for x in report["blames"] if "rank 0 blocked" in x]
    assert b, report["blames"]
    assert "era agreement round 2 on cid 3" in b[0]
    assert "waiting on rank 1's vote" in b[0]
    assert "never entered agreement round 2" in b[0]
    assert "no stalled rank" not in mpidiag.render(report)


def test_mpidiag_handler_created_round_reads_as_never_entered():
    """Round state whose members is null was created by the background
    era handler from a peer's eager contribution — the rank never
    called agree(); blaming it as 'entered and exited' would send
    triage down the wrong path (5th review pass)."""
    dumps = {
        0: _dump(0, latched=True, reason="stall-sentinel"),
        2: _dump(2),
    }
    dumps[0]["subsystems"]["ft.era"] = {"rounds": [
        _era_round(3, 2, [0, 2], [0], [2])]}
    dumps[2]["subsystems"]["ft.era"] = {"rounds": [
        _era_round(3, 2, None, [3], None, in_progress=False)]}
    b = [x for x in mpidiag.analyze(dumps)["blames"]
         if "rank 0 blocked" in x][0]
    assert "never entered agreement round 2" in b
    assert "entered and exited" not in b


def test_mpidiag_era_member_blames_lost_decide():
    dumps = {
        1: _dump(1, latched=True, reason="stall-sentinel"),
        0: _dump(0),
    }
    # rank 1 is a member of round 4 waiting for rank 0's broadcast;
    # rank 0 already decided — the DECIDE frame was lost
    dumps[1]["subsystems"]["ft.era"] = {"rounds": [
        _era_round(3, 4, [0, 1], [1], [0])]}
    dumps[0]["subsystems"]["ft.era"] = {"rounds": [
        _era_round(3, 4, [0, 1], [0, 1], [], in_progress=False,
                   decision=True)]}
    b = mpidiag.analyze(dumps)["blames"][0]
    assert "waiting on rank 0's decision broadcast" in b
    assert "DECIDE frame" in b and "lost" in b


def test_mpidiag_peer_request_dumps_not_blamed():
    """Healthy peers' dumps inherit the requester's reason text; their
    routine in-flight receives must not be blamed when the stalled
    rank's own dump is missing."""
    dumps = {2: _dump(2, reason="peer-request: stall-sentinel on rank 1",
                      pml={"matching": {"posted": [
                          {"cid": 0, "src": 0, "tag": 7, "n": 1,
                           "oldest_pseq": 0, "oldest_age_s": 0.1}]}})}
    report = mpidiag.analyze(dumps)
    assert not report["blames"], report["blames"]


def test_mpidiag_latched_rank_without_edges_still_reported():
    report = mpidiag.analyze(
        {0: _dump(0, latched=True, reason="stall-sentinel")})
    assert report["blames"], "latched rank vanished from the verdict"
    assert "no pml/era waiting-on edge" in report["blames"][0]
    assert "no stalled rank" not in mpidiag.render(report)


def test_mpidiag_healthy_dumps_blame_nothing():
    report = mpidiag.analyze({0: _dump(0), 1: _dump(1)})
    assert not report["blames"] and not report["cycles"]
    assert "no stalled rank" in mpidiag.render(report)


def test_mpidiag_missing_peer_dump_local_fallback():
    dumps = {1: _dump(1, latched=True, pml={
        "matching": {"posted": [
            {"cid": 0, "src": 0, "tag": 7, "n": 1,
             "oldest_pseq": 0, "oldest_age_s": 3.0}]}})}
    b = mpidiag.analyze(dumps)["blames"][0]
    assert "no dump from rank 0" in b and "rank-local evidence" in b


def test_mpidiag_reads_dir_and_cli(tmp_path):
    for r in (0, 1):
        (tmp_path / f"stall-rank{r}.json").write_text(
            json.dumps(_dump(r)))
    dumps = mpidiag.read_dumps(str(tmp_path))
    assert sorted(dumps) == [0, 1]
    assert mpidiag.main(["--dir", str(tmp_path)]) == 0
    assert mpidiag.main(["--dir", str(tmp_path / "nope")]) == 1


# ------------------------------------------------------- mpitop column
def test_mpitop_stall_cell_sampler_and_pvar_fallback():
    snap = {"samplers": {"forensics_stall":
                         {"latched": 1, "age_s": 12.4}}}
    assert mpitop.stall_cell(snap) == "*12s"
    snap = {"samplers": {"forensics_stall":
                         {"latched": 0, "age_s": 3.0}}}
    assert mpitop.stall_cell(snap) == "3s"
    # pvar fallback (snapshot written before the sampler existed)
    snap = {"pvars": {"forensics_stall_latched": 1,
                      "forensics_last_completion_age_s": 7.0}}
    assert mpitop.stall_cell(snap) == "*7s"
    assert mpitop.stall_cell({"pvars": {}}) == ""


def test_stall_sampler_in_metrics_snapshot():
    from ompi_tpu.runtime import metrics as _metrics

    snap = _metrics.snapshot()
    row = snap["samplers"]["forensics_stall"]
    assert set(row) == {"latched", "age_s", "trips", "dumps"}


# ------------------------------------------------- abort/fatal exports
def test_trace_export_on_fatal_and_reentrancy(tmp_path, restore_vars):
    restore_vars("trace", "dir")
    restore_vars("trace", "enable")
    set_var("trace", "dir", str(tmp_path))
    set_var("trace", "enable", True)
    with _trace.span("unit.fatal", cat="test"):
        pass
    _trace.export_on_fatal()
    path = tmp_path / "trace-rank0.json"
    assert path.exists()
    doc = json.loads(path.read_text())
    assert any(e.get("name") == "unit.fatal"
               for e in doc["traceEvents"])
    # does NOT consume the finalize export
    assert not _trace._exported
    # re-entrancy guard: a nested call while exporting is a no-op, and
    # the flag always resets
    assert not _trace._fatal_exporting[0]


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_progress_thread_fatal_exports_ring(tmp_path, restore_vars):
    from ompi_tpu.runtime.progress import (ProgressThread,
                                           register_progress,
                                           unregister_progress)

    restore_vars("trace", "dir")
    restore_vars("trace", "enable")
    set_var("trace", "dir", str(tmp_path))
    set_var("trace", "enable", True)
    with _trace.span("unit.progress-fatal", cat="test"):
        pass

    def die():
        if threading.current_thread().name == "ompi-tpu-progress":
            raise SystemExit("seeded progress-thread death")
        return 0

    register_progress(die)
    t = ProgressThread(interval=0.001)
    try:
        t.start()
        deadline = time.monotonic() + 8.0
        while t._thread is not None and t._thread.is_alive() and \
                time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        unregister_progress(die)
        t.stop()
    path = tmp_path / "trace-rank0.json"
    assert path.exists(), "dying progress thread did not export rings"
    assert any(e.get("name") == "unit.progress-fatal"
               for e in json.loads(path.read_text())["traceEvents"])


# ------------------------------------------------------ era timeout detail
class _DummyPml:
    my_rank = 0

    def register_system_handler(self, tag, fn):
        pass

    def isend(self, *a, **kw):
        raise OSError("no wire in this unit test")


def test_era_timeout_names_round_bitmask_and_outstanding(restore_vars):
    from ompi_tpu.ft.era import EraEngine

    restore_vars("ft", "era_timeout")
    set_var("ft", "era_timeout", 0.2)

    class _Comm:
        cid = 77
        revoked = False

        class group:
            ranks = [0, 1]

    eng = EraEngine(_DummyPml())
    with pytest.raises(MPIError) as ei:
        eng.agree(_Comm(), 1)
    assert ei.value.code == ERR_PENDING
    msg = str(ei.value)
    assert "agreement round 0 on cid 77" in msg
    assert "participant bitmask 0x1" in msg  # only rank 0 contributed
    assert "votes outstanding [1]" in msg
    assert "members [0, 1]" in msg


def test_participant_bitmask_positional():
    from ompi_tpu.ft.era import _participant_bitmask

    assert _participant_bitmask([4, 9, 200], [4, 200]) == 0b101
    assert _participant_bitmask(None, [2, 5]) == (1 << 2) | (1 << 5)
    assert _participant_bitmask([1, 2], []) == 0


def test_era_timeout_topic_registered():
    from ompi_tpu.utils.show_help import _messages

    assert ("ft", "era-timeout") in _messages
    assert ("forensics", "stall") in _messages


# -------------------------------------------------------- registration
def test_cvars_pvars_registered():
    vs = all_vars()
    assert "forensics_enable" in vs
    assert "forensics_stall_threshold_ms" in vs
    pv = all_pvars()
    for name in ("forensics_stall_trips", "forensics_dumps",
                 "forensics_stall_latched",
                 "forensics_last_completion_age_s"):
        assert name in pv, name
        pv[name].value  # readable


def test_qos_tag_map_promotes_forensics_tag():
    from ompi_tpu import qos

    qos.reset_for_testing()
    try:
        assert qos._tag_class(fx.FORENSICS_TAG) == qos.LATENCY
    finally:
        qos.reset_for_testing()


def test_forensics_tag_in_mpiracer_registry():
    """The -4800 plane must appear in mpiracer's --json tag registry,
    handled and sent (the satellite's machine-checkable half)."""
    from ompi_tpu.analysis import pkgmodel, protocol

    pkg = pkgmodel.load_package([os.path.join(REPO, "ompi_tpu")])
    reg = protocol.registry_json(pkg)
    ent = [t for t in reg["tags"] if t["value"] == fx.FORENSICS_TAG]
    assert ent, "FORENSICS_TAG missing from the protocol registry"
    assert ent[0]["name"] == "FORENSICS_TAG"
    assert ent[0]["handled"] and ent[0]["sent"]


def test_info_cli_loads_forensics(capsys):
    from ompi_tpu.tools import info

    info.main(["--level", "9", "--param", "forensics"])
    out = capsys.readouterr().out
    assert "forensics_enable" in out
    assert "forensics_stall_threshold_ms" in out


# ---------------------------------------------------------- procmode
def test_procmode_seeded_stall_names_blocking_edge(tmp_path):
    """The acceptance gate: a drop-all stall on the 0 -> 1 edge produces
    per-rank dumps and a merged mpidiag blame naming the true blocking
    edge — 5/5 episodes deterministic."""
    r = run_mpi(3, "tests/procmode/check_forensics.py", "stall", "5",
                timeout=240,
                mca=(("btl_btl", "^sm"),
                     ("forensics_enable", "1"),
                     ("forensics_stall_threshold_ms", "400"),
                     ("ft_inject_plan", "drop(0,1,side=recv)")),
                env_extra=(("OMPI_TPU_MCA_metrics_dir",
                            str(tmp_path)),))
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
    oks = [ln for ln in r.stdout.splitlines()
           if "FORENSICS-EP" in ln and "-OK" in ln]
    assert len(oks) == 5, r.stdout
    assert all("rank 1 blocked on MATCH" in ln for ln in oks), oks
    assert "FORENSICS-STALL-OK episodes=5" in r.stdout
    # the dumps stay on disk for post-mortem tooling
    diag = mpidiag.read_dumps(str(tmp_path))
    assert sorted(diag) == [0, 1, 2]


def test_procmode_ondemand_dump_clean(tmp_path):
    r = run_mpi(3, "tests/procmode/check_forensics.py", "ondemand",
                timeout=240,
                env_extra=(("OMPI_TPU_MCA_metrics_dir",
                            str(tmp_path)),))
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
    assert r.stdout.count("FORENSICS-ONDEMAND-OK") == 3


def test_procmode_abort_exports_trace_ring(tmp_path):
    r = run_mpi(2, "tests/procmode/check_crash.py", timeout=240,
                mca=(("trace_enable", "1"),),
                env_extra=(("OMPI_TPU_MCA_trace_dir", str(tmp_path)),))
    assert r.returncode != 0  # the job aborted, as seeded
    path = tmp_path / "trace-rank1.json"
    assert path.exists(), f"abort lost the ring\n{r.stdout}\n{r.stderr}"
    doc = json.loads(path.read_text())
    names = {e.get("name") for e in doc["traceEvents"]}
    assert "pml.send" in names  # real spans, not an empty shell
