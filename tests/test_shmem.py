"""OpenSHMEM layer (reference: oshmem/ — spml put/get over the osc
engine, memheap symmetric allocation, scoll delegating to MPI coll)."""

import numpy as np
import pytest

from ompi_tpu import shmem
from tests.test_process_mode import run_mpi


def test_shmem_singleton_roundtrip():
    shmem.init()
    assert shmem.n_pes() == 1 and shmem.my_pe() == 0
    a = shmem.zeros(4, np.float64)
    shmem.put(a, [1.0, 2.0, 3.0, 4.0], pe=0)
    shmem.quiet()
    np.testing.assert_array_equal(a.local, [1, 2, 3, 4])
    np.testing.assert_array_equal(shmem.get(a, 4, pe=0), [1, 2, 3, 4])
    assert shmem.atomic_fetch_add(a, 10.0, pe=0) == 1.0
    assert a.local[0] == 11.0
    assert shmem.atomic_compare_swap(a, 11.0, 99.0, pe=0) == 11.0
    assert a.local[0] == 99.0
    shmem.barrier_all()


def test_shmem_symmetric_offsets_and_heap_guard():
    shmem.init()
    x = shmem.zeros(2, np.int64)
    y = shmem.zeros(2, np.int64)
    assert y.off > x.off and y.off % 16 == 0
    from ompi_tpu.core.errors import MPIError

    with pytest.raises(MPIError):
        shmem.zeros(1 << 30, np.float64)  # heap exhausted


def test_shmem_procmode_4_pes():
    r = run_mpi(4, "tests/procmode/check_shmem.py")
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("SHMEM-OK") == 4


def test_shmem_procmode_3_pes():
    r = run_mpi(3, "tests/procmode/check_shmem.py")
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("SHMEM-OK") == 3


def test_free_rejects_double_free_and_foreign_spans():
    """r3 advisor: free() must validate the span is live — a double
    free (or stale handle) would coalesce into overlap and the heap
    would hand the same bytes out twice."""
    import numpy as np
    import pytest

    import ompi_tpu.shmem as shmem
    from ompi_tpu.core.errors import MPIError

    shmem.init()
    try:
        a = shmem.zeros(8, np.int32)
        shmem.free(a)
        with pytest.raises(MPIError):
            shmem.free(a)  # double free
        b = shmem.zeros(4, np.int32)
        fake = shmem.SymArray(b.off + 4, 4, np.dtype(np.int32),
                              np.zeros(4, np.int32))
        with pytest.raises(MPIError):
            shmem.free(fake)  # foreign span inside a live block
        shmem.free(b)
    finally:
        shmem.finalize()
