"""Flagship transformer: dp x sp x tp training step on the virtual mesh."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ompi_tpu.models import transformer as tfm


CFG = tfm.Config(vocab=64, d_model=32, n_heads=8, n_layers=2, d_ff=64,
                 seq_len=16)


def _data(cfg, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    toks = rng.randint(0, cfg.vocab, size=(batch, cfg.seq_len)).astype(np.int32)
    targets = np.roll(toks, -1, axis=1).astype(np.int32)
    return jnp.asarray(toks), jnp.asarray(targets)


def test_single_device_forward():
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    toks, _ = _data(CFG)
    logits = jax.jit(lambda p, t: tfm.forward(p, t, CFG))(params, toks)
    assert logits.shape == (8, CFG.seq_len, CFG.vocab)
    assert np.isfinite(np.asarray(logits)).all()


def _mesh(dp, sp, tp):
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[: dp * sp * tp]).reshape(dp, sp, tp)
    return Mesh(devs, ("dp", "sp", "tp"))


# Two layouts keep the suite under the 5-minute CI budget (VERDICT r1 weak
# #10): (2,2,2) exercises all three axes at once, (2,1,4) the deep-tp mix.
# Pure-dp (8,1,1) and pure-tp (1,1,8) are corner cases of the same code
# paths; enable with OMPI_TPU_TEST_ALL_LAYOUTS=1 for exhaustive runs.
_LAYOUTS = [(2, 2, 2), (2, 1, 4)]
if os.environ.get("OMPI_TPU_TEST_ALL_LAYOUTS"):
    _LAYOUTS += [(8, 1, 1), (1, 1, 8)]


@pytest.fixture(scope="module")
def single_step_trajectory():
    """3-step single-device loss trajectory, computed ONCE — each layout
    compares against the same reference instead of recompiling it."""
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    toks, tgts = _data(CFG, batch=8)
    mesh1 = _mesh(1, 1, 1)
    step1, place1 = tfm.make_train_step(mesh1, CFG)
    p1, t1, g1 = place1(params, toks, tgts)
    losses = []
    for _ in range(3):
        loss1, p1 = step1(p1, t1, g1)
        losses.append(float(loss1))
    return losses, jax.tree.map(np.asarray, p1)


@pytest.mark.parametrize("dp,sp,tp", _LAYOUTS)
def test_train_step_parallel_matches_single(dp, sp, tp,
                                            single_step_trajectory):
    """The sharded training step must compute the same loss/params as the
    single-device step (the reference-correctness bar for every layout)."""
    mesh = _mesh(dp, sp, tp)
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    toks, tgts = _data(CFG, batch=8)

    step, place = tfm.make_train_step(mesh, CFG)
    p_sh, t_sh, g_sh = place(params, toks, tgts)

    ref_losses, ref_params = single_step_trajectory
    # a layout bug (e.g. mis-sharded qkv) shifts the loss ~1e-2 and
    # compounds over steps; bf16 accumulation-order noise stays ~1e-4
    for i in range(3):
        loss_sharded, p_sh = step(p_sh, t_sh, g_sh)
        np.testing.assert_allclose(float(loss_sharded), ref_losses[i],
                                   rtol=2e-3)
    for a, b in zip(jax.tree.leaves(p_sh), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-1, atol=1e-2)


def test_training_reduces_loss():
    mesh = _mesh(2, 2, 2)
    params = tfm.init_params(jax.random.PRNGKey(2), CFG)
    toks, tgts = _data(CFG, batch=8, seed=5)
    step, place = tfm.make_train_step(mesh, CFG)
    params, toks, tgts = place(params, toks, tgts)
    losses = []
    for _ in range(8):
        loss, params = step(params, toks, tgts)
        losses.append(float(loss))
    assert all(b < a for a, b in zip(losses, losses[1:])), losses
    assert losses[0] - losses[-1] > 0.15, losses
