"""C binding: classic MPI C programs against libompi_tpu_c
(reference: ompi/mpi/c bindings + the mpicc wrapper contract).

Compiles examples/ring_c.c with the mpicc wrapper and runs it as real
multi-rank jobs through the launcher — C binaries exec directly and
their embedded runtime reads the same OMPI_TPU_* launch contract.
"""

import os
import shutil
import subprocess
import sys

import pytest

from tests.test_process_mode import REPO, subprocess_env

pytestmark = pytest.mark.skipif(shutil.which("cc") is None,
                                reason="no C compiler")


@pytest.fixture(scope="module")
def ring_bin(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("capi") / "ring_c")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpicc",
         "examples/ring_c.c", "-o", out],
        cwd=REPO, capture_output=True, text=True, timeout=240,
        env=subprocess_env())
    assert r.returncode == 0, r.stdout + r.stderr
    return out


def test_mpicc_showme():
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpicc", "--showme"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
        env=subprocess_env())
    assert r.returncode == 0, r.stderr
    assert "-lompi_tpu_c" in r.stdout and "-I" in r.stdout


def test_c_ring_4_ranks(ring_bin):
    """BASELINE ladder #1 shape, but the ranks are C binaries."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "4",
         ring_bin],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env=subprocess_env())
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Process 0 decremented value: 0" in r.stdout
    assert r.stdout.count("exiting") == 4
    assert "Allreduce sum of ranks: 6" in r.stdout


def test_c_collectives_and_status(tmp_path):
    """bcast/allgather/reduce/status/Get_count (incl. the
    partial-element MPI_UNDEFINED contract) from C."""
    out = str(tmp_path / "coll_c")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpicc",
         "examples/coll_c.c", "-o", out],
        cwd=REPO, capture_output=True, text=True, timeout=240,
        env=subprocess_env())
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "4", out],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env=subprocess_env())
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("COLL-C-OK") == 4


def test_c_ring_2_ranks_tcp_only(ring_bin):
    """The same binary over the tcp rail (no shared memory)."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
         "--mca", "btl_btl", "^sm", ring_bin],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env=subprocess_env())
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Allreduce sum of ranks: 1" in r.stdout
