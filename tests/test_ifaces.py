"""if/reachable: interface inventory + weighted peer reachability
(reference: opal/mca/if + opal/mca/reachable)."""

import socket

import pytest

from ompi_tpu.runtime import ifaces


def test_list_interfaces_sees_loopback():
    lst = ifaces.list_interfaces()
    assert lst, "no interfaces discovered"
    lo = [i for i in lst if i.loopback]
    assert lo and lo[0].addr.startswith("127."), lst


def test_weight_ordering():
    lo = ifaces.Iface("lo", "127.0.0.1", "255.0.0.0", True, True)
    eth = ifaces.Iface("eth0", "10.1.2.3", "255.255.255.0", True, False)
    down = ifaces.Iface("eth1", "10.9.9.9", "255.255.255.0", False, False)
    # same subnet wins over routable; loopback only matches loopback
    assert ifaces.weight(eth, "10.1.2.50") > ifaces.weight(eth, "8.8.8.8")
    assert ifaces.weight(lo, "127.0.0.1") > 0
    assert ifaces.weight(lo, "10.1.2.50") == 0
    assert ifaces.weight(eth, "127.0.0.1") == 0
    assert ifaces.weight(down, "10.9.9.1") < 0


def test_pick_source_loopback_peer():
    src = ifaces.pick_source("127.0.0.1")
    assert src is None or src.startswith("127."), src


def test_best_local_addr_resolves():
    addr = ifaces.best_local_addr()
    assert addr is not None
    socket.inet_aton(addr)  # parseable IPv4
