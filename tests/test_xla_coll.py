"""coll/xla collective tests on the virtual 8-device CPU mesh.

These validate the flagship path: MPI collectives lowered to XLA HLO with
axis_index_groups projecting sub-communicators (reference semantics from
coll/base algorithms, executed as single collective HLO ops)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ompi_tpu.core import op as mpi_op
from ompi_tpu.parallel import mesh_world

W = 8


@pytest.fixture(scope="module")
def world():
    assert jax.device_count() >= W, "conftest must force 8 CPU devices"
    return mesh_world(jax.devices()[:W])


def _ranked(shape=(4,), dtype=np.float32):
    """Per-rank distinct data: row r = r + arange."""
    base = np.arange(np.prod(shape), dtype=dtype).reshape(shape)
    return np.stack([base + r for r in range(W)])


def test_allreduce_sum(world):
    x = world.shard(_ranked())
    r = np.asarray(world.allreduce(x))
    expect = np.stack([_ranked().sum(0)] * W)
    np.testing.assert_allclose(r, expect)


def test_allreduce_max_min(world):
    x = world.shard(_ranked())
    np.testing.assert_allclose(
        np.asarray(world.allreduce(x, mpi_op.MAX)),
        np.stack([_ranked().max(0)] * W),
    )
    np.testing.assert_allclose(
        np.asarray(world.allreduce(x, mpi_op.MIN)),
        np.stack([_ranked().min(0)] * W),
    )


def test_allreduce_prod_gather_path(world):
    data = np.full((W, 3), 2.0, np.float32)
    x = world.shard(data)
    r = np.asarray(world.allreduce(x, mpi_op.PROD))
    np.testing.assert_allclose(r, np.full((W, 3), 2.0**W))


def test_allreduce_band(world):
    data = np.stack([np.full(4, 0b1111 ^ (1 << (r % 4)), np.int32)
                     for r in range(W)])
    x = world.shard(data)
    r = np.asarray(world.allreduce(x, mpi_op.BAND))
    expect = np.bitwise_and.reduce(data, axis=0)
    np.testing.assert_array_equal(r, np.stack([expect] * W))


def test_allreduce_bool_land(world):
    data = np.ones((W, 4), dtype=bool)
    data[3, 2] = False
    x = world.shard(data)
    r = np.asarray(world.allreduce(x, mpi_op.LAND))
    expect = data.all(axis=0)
    np.testing.assert_array_equal(r, np.stack([expect] * W))


def test_bcast(world):
    data = _ranked()
    x = world.shard(data)
    r = np.asarray(world.bcast(x, root=3))
    np.testing.assert_allclose(r, np.stack([data[3]] * W))
    # different root must NOT recompile (root is traced); just check value
    r5 = np.asarray(world.bcast(x, root=5))
    np.testing.assert_allclose(r5, np.stack([data[5]] * W))


def test_allgather(world):
    data = _ranked()
    x = world.shard(data)
    r = np.asarray(world.allgather(x))
    assert r.shape == (W, W, 4)
    for i in range(W):
        np.testing.assert_allclose(r[i], data)


def test_alltoall(world):
    data = np.arange(W * W * 2, dtype=np.float32).reshape(W, W, 2)
    x = world.shard(data)
    r = np.asarray(world.alltoall(x))
    for i in range(W):
        for j in range(W):
            np.testing.assert_allclose(r[i, j], data[j, i])


def test_reduce_scatter(world):
    data = np.arange(W * W * 3, dtype=np.float32).reshape(W, W, 3)
    x = world.shard(data)
    r = np.asarray(world.reduce_scatter(x))
    expect = data.sum(axis=0)  # [W, 3]
    np.testing.assert_allclose(r, expect)


def test_scan_exscan(world):
    data = _ranked()
    x = world.shard(data)
    r = np.asarray(world.scan(x))
    expect = np.cumsum(data, axis=0)
    np.testing.assert_allclose(r, expect)
    re = np.asarray(world.exscan(x))
    np.testing.assert_allclose(re[0], np.zeros(4))
    np.testing.assert_allclose(re[1:], expect[:-1])


def test_barrier(world):
    world.barrier()  # must not deadlock/throw


def test_shift_ring(world):
    data = _ranked()
    x = world.shard(data)
    r = np.asarray(world.shift(x, 1))
    np.testing.assert_allclose(r, np.roll(data, 1, axis=0))


def test_split_subcomm_allreduce(world):
    colors = [r % 2 for r in range(W)]  # evens vs odds
    sub = world.Split(colors)
    assert sub.size == W // 2
    data = _ranked()
    x = sub.shard(data)
    r = np.asarray(sub.allreduce(x))
    evens = sum(data[i] for i in range(0, W, 2))
    odds = sum(data[i] for i in range(1, W, 2))
    for i in range(W):
        np.testing.assert_allclose(r[i], evens if i % 2 == 0 else odds)


def test_split_keys_reorder_bcast(world):
    # one color, reversed keys: comm-rank 0 is mesh rank W-1
    sub = world.Split([0] * W, keys=list(range(W - 1, -1, -1)))
    data = _ranked()
    r = np.asarray(sub.bcast(sub.shard(data), root=0))
    np.testing.assert_allclose(r, np.stack([data[W - 1]] * W))


def test_create_group_padding(world):
    sub = world.Create_group([1, 2, 5])
    data = _ranked()
    r = np.asarray(sub.allreduce(sub.shard(data)))
    expect = data[1] + data[2] + data[5]
    for i in (1, 2, 5):
        np.testing.assert_allclose(r[i], expect)


def test_subcomm_alltoall(world):
    colors = [0, 0, 0, 0, 1, 1, 1, 1]
    sub = world.Split(colors)
    g = sub.size
    data = np.arange(W * g * 2, dtype=np.float32).reshape(W, g, 2)
    r = np.asarray(sub.alltoall(sub.shard(data)))
    for grp in ([0, 1, 2, 3], [4, 5, 6, 7]):
        for pi, i in enumerate(grp):
            for pj, j in enumerate(grp):
                np.testing.assert_allclose(r[i, pj], data[j, pi])


def test_compile_cache_reuse(world):
    key = ("allreduce", mpi_op.SUM.uid)
    x = world.shard(_ranked())
    world.allreduce(x)
    f1 = world._jit_cache.get(key)
    assert f1 is not None
    world.allreduce(x)
    assert world._jit_cache.get(key) is f1


def test_coll_selection_is_xla(world):
    assert world.coll.providers["allreduce"] == "xla"


def test_land_lor_on_ints(world):
    """Regression: logical ops must reduce truthiness, not numeric min/max."""
    data = np.zeros((W, 2), np.int32)
    data[:, 0] = -3       # all nonzero -> LAND true
    data[2, 1] = 0        # one zero -> LAND false
    data[:, 1] = [-3, 5, 0, 1, 2, 3, 4, 5]
    x = world.shard(data)
    land = np.asarray(world.allreduce(x, mpi_op.LAND))
    assert land[0, 0] == 1 and land[0, 1] == 0
    lor_data = np.zeros((W, 2), np.int32)
    lor_data[4, 0] = -7   # one nonzero -> LOR true
    lx = world.shard(lor_data)
    lor = np.asarray(world.allreduce(lx, mpi_op.LOR))
    assert lor[0, 0] == 1 and lor[0, 1] == 0


def test_user_ops_distinct_cache(world):
    """Regression: two user ops must not share a compiled executable."""
    f_add = mpi_op.Op.Create(lambda a, b: a + b)
    f_mul = mpi_op.Op.Create(lambda a, b: a * b)
    data = np.full((W, 2), 2.0, np.float32)
    x = world.shard(data)
    r_add = np.asarray(world.allreduce(x, f_add))
    r_mul = np.asarray(world.allreduce(x, f_mul))
    np.testing.assert_allclose(r_add[0], [16.0, 16.0])
    np.testing.assert_allclose(r_mul[0], [256.0, 256.0])


def test_split_undefined_shift(world):
    """Regression: shift on a comm with UNDEFINED (singleton) padding."""
    from ompi_tpu.parallel.mesh import UNDEFINED

    colors = [0, 0, 0, 0, UNDEFINED, UNDEFINED, UNDEFINED, UNDEFINED]
    sub = world.Split(colors)
    data = _ranked()
    r = np.asarray(sub.shift(sub.shard(data), 1))
    np.testing.assert_allclose(r[1], data[0])
    np.testing.assert_allclose(r[0], data[3])


def test_bcast_root_out_of_range(world):
    import pytest as _pytest
    from ompi_tpu.core.errors import MPIError

    x = world.shard(_ranked())
    with _pytest.raises(MPIError):
        world.bcast(x, root=12)


def test_grouped_land_ints(world):
    sub = world.Split([r % 2 for r in range(W)])
    data = np.full((W, 2), 7, np.int32)
    data[2, 0] = 0  # even group: one zero
    r = np.asarray(sub.allreduce(sub.shard(data), mpi_op.LAND))
    assert r[0, 0] == 0 and r[0, 1] == 1
    assert r[1, 0] == 1


def test_ulfm_surface_singleton():
    from ompi_tpu import COMM_WORLD

    d = COMM_WORLD.Dup()
    assert d.Agree(0b1011) == 0b1011
    d.Revoke()
    from ompi_tpu.core.errors import MPIError
    import pytest as _pytest

    with _pytest.raises(MPIError):
        d.Barrier()


# ------------------- r2: pair ops, non-uniform splits, real movers -------
def test_device_minloc_maxloc(world):
    """MINLOC/MAXLOC lower to device pair reductions ([..., 2] layout),
    replacing the r1 host-only restriction (reference: op/avx pair
    kernels over MPI_FLOAT_INT)."""
    vals = np.array([5., 3., 7., 3., 9., 1., 4., 1.])
    pairs = np.stack([vals, np.arange(8.)], axis=-1)[:, None, :]
    out = np.asarray(world.allreduce(world.shard(pairs), op=mpi_op.MINLOC))
    np.testing.assert_array_equal(out[0, 0], [1.0, 5.0])
    out = np.asarray(world.allreduce(world.shard(pairs), op=mpi_op.MAXLOC))
    np.testing.assert_array_equal(out[0, 0], [9.0, 4.0])


def test_device_pair_op_needs_pair_layout(world):
    from ompi_tpu.core.errors import MPIError

    with pytest.raises(MPIError):
        world.allreduce(world.shard(np.zeros((8, 3))), op=mpi_op.MINLOC)


def test_nonuniform_split_allreduce_bcast_scan(world):
    """Arbitrary Split shapes (the reference supports any color layout,
    comm.c) — r1 raised ERR_UNSUPPORTED for mixed group sizes."""
    sub = world.Split([0, 0, 0, 1, 1, 2, 3, 3])
    x = sub.shard(np.arange(8, dtype=np.float32)[:, None] + 1)
    out = np.asarray(sub.allreduce(x))
    np.testing.assert_array_equal(out[:, 0], [6, 6, 6, 9, 9, 6, 15, 15])
    out = np.asarray(sub.bcast(x, root=0))
    np.testing.assert_array_equal(out[:, 0], [1, 1, 1, 4, 4, 6, 7, 7])
    out = np.asarray(sub.scan(x))
    np.testing.assert_array_equal(out[:, 0], [1, 3, 6, 4, 9, 6, 7, 15])


def test_scatter_real_semantics(world):
    """Group rank p receives ROOT's chunk p (the r1 stub just resharded
    the input, ignoring the root)."""
    chunks = np.zeros((8, 8, 1), np.float32)
    chunks[2] = np.arange(8)[:, None] * 10.0
    out = np.asarray(world.scatter(world.shard(chunks), root=2))
    np.testing.assert_array_equal(out[:, 0], np.arange(8) * 10.0)


def test_scatter_grouped(world):
    sub = world.Split([0, 0, 0, 0, 1, 1, 1, 1])
    chunks = np.zeros((8, 4, 1), np.float32)
    chunks[1] = np.arange(4)[:, None] + 100  # root 1 of group 0
    chunks[5] = np.arange(4)[:, None] + 200  # root 1 of group 1
    out = np.asarray(sub.scatter(sub.shard(chunks), root=1))
    np.testing.assert_array_equal(out[:4, 0], np.arange(4) + 100)
    np.testing.assert_array_equal(out[4:, 0], np.arange(4) + 200)


def test_gather_root_rows(world):
    x = world.shard(np.arange(8, dtype=np.float32)[:, None])
    out = np.asarray(world.gather(x, root=0))
    np.testing.assert_array_equal(out[0, :, 0], np.arange(8))


def test_mesh_agree_band(world):
    """MPIX_Comm_agree on a mesh comm: BAND under the single controller
    (the pml-less branch of ft/agreement.agree)."""
    assert world.Agree(0b1011) == 0b1011
