"""Checkpoint/resume: orbax mesh training state + rank-partitioned
process-mode checkpoints (SURVEY §5 aux subsystem)."""

import numpy as np
import pytest

import jax

from tests.test_process_mode import run_mpi

W = 8


@pytest.fixture(scope="module")
def mesh_bits():
    from jax.sharding import Mesh

    from ompi_tpu.models.transformer import (
        Config, init_params, make_train_step, param_specs)

    assert jax.device_count() >= W
    cfg = Config(vocab=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
                 seq_len=32)
    mesh = Mesh(np.asarray(jax.devices()[:W]).reshape(2, 2, 2),
                ("dp", "sp", "tp"))
    step_fn, place = make_train_step(mesh, cfg)
    return cfg, mesh, step_fn, place


def _data(cfg, seed, batch=4):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, cfg.vocab, (batch, cfg.seq_len),
                        dtype=np.int32)
    return toks, np.roll(toks, -1, axis=1)


def test_mesh_train_checkpoint_resume_identical(tmp_path, mesh_bits):
    from ompi_tpu.models.transformer import init_params, param_specs
    from ompi_tpu.runtime.checkpoint import MeshCheckpointer

    cfg, mesh, step_fn, place = mesh_bits
    params = init_params(jax.random.PRNGKey(0), cfg)
    toks, tgts = _data(cfg, 7)
    params, dtoks, dtgts = place(params, toks, tgts)

    # uninterrupted: 5 steps (keep the last two losses as ground truth)
    ref = params
    ref_losses = []
    for i in range(5):
        loss, ref = step_fn(ref, dtoks, dtgts)
        if i >= 3:
            ref_losses.append(float(loss))

    # interrupted: 3 steps, checkpoint, "restart", 2 more steps
    ck = MeshCheckpointer(str(tmp_path / "mesh_ck"))
    p = params
    for _ in range(3):
        _, p = step_fn(p, dtoks, dtgts)
    ck.save(3, jax.tree.map(np.asarray, p))
    assert ck.latest_step() == 3

    restored = ck.restore(mesh=mesh, specs=param_specs(cfg))
    resumed_losses = []
    for _ in range(2):
        loss, restored = step_fn(restored, dtoks, dtgts)
        resumed_losses.append(float(loss))
    ck.close()
    assert resumed_losses == ref_losses  # step-for-step identical

    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_mesh_checkpoint_retention(tmp_path):
    from ompi_tpu.runtime.checkpoint import MeshCheckpointer

    ck = MeshCheckpointer(str(tmp_path / "ret"), max_to_keep=2)
    for s in (1, 2, 3):
        ck.save(s, {"a": np.full(2, float(s))})
    assert ck.latest_step() == 3
    got = ck.restore()
    np.testing.assert_array_equal(got["a"], [3.0, 3.0])
    ck.close()


def test_procmode_checkpoint_restart(tmp_path):
    ckdir = str(tmp_path / "ranked")
    r = run_mpi(3, "tests/procmode/check_checkpoint.py", ckdir, "save",
                timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("CKPT-SAVED") == 3
    r2 = run_mpi(3, "tests/procmode/check_checkpoint.py", ckdir,
                 "resume", timeout=120)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert r2.stdout.count("CKPT-RESUMED") == 3


def test_procmode_checkpoint_size_mismatch(tmp_path):
    ckdir = str(tmp_path / "ranked2")
    r = run_mpi(2, "tests/procmode/check_checkpoint.py", ckdir, "save",
                timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    r2 = run_mpi(3, "tests/procmode/check_checkpoint.py", ckdir,
                 "resume", timeout=120)
    assert r2.returncode != 0
    assert "repartitioning" in (r2.stdout + r2.stderr)


def test_restore_rank_override_validated_against_geometry(tmp_path):
    """Satellite (PR 5): the shrink-recovery ``rank=`` override must be
    range-checked against the COMMITTED manifest geometry — an
    out-of-range override raises a clean MPIError(ERR_FILE) instead of
    a confusing missing-file error or a silent foreign read."""
    from ompi_tpu.core.errors import MPIError, ERR_FILE
    from ompi_tpu.runtime.checkpoint import restore_ranked, save_ranked
    from ompi_tpu.runtime.state import get_world

    w = get_world()  # singleton: manifest geometry is 1 rank
    ckdir = str(tmp_path / "ranked3")
    save_ranked(w, ckdir, 4, {"x": np.arange(3.0)})
    got = restore_ranked(w, ckdir, 4, rank=0)  # valid override
    np.testing.assert_array_equal(got["x"], np.arange(3.0))
    for bad in (1, -1, 99):
        with pytest.raises(MPIError) as ei:
            restore_ranked(w, ckdir, 4, rank=bad)
        assert ei.value.code == ERR_FILE
        assert "out of range" in str(ei.value)


def test_torn_attempt_is_invisible(tmp_path):
    """A step dir without a committed manifest is never restored."""
    import os

    from ompi_tpu.runtime.checkpoint import latest_ranked_step

    d = tmp_path / "torn" / "step_0000000007"
    os.makedirs(d)
    (d / "rank_0.npz").write_bytes(b"partial")
    assert latest_ranked_step(str(tmp_path / "torn")) is None
