"""Collective round engine: zero-copy datapath, pooled-recv ownership,
and round windowing (coll/sched.py, PR 10).

Unit level: a fake loopback pml drives the real engine so the ownership
contract (recycle on completion / Round.free, DISCARD on failure) and
the window semantics are provable without subprocesses. End-to-end
numbers + bitwise A/B live in tests/procmode/check_coll_round.py and
bench.py's coll_datapath section.
"""

import threading
import time
from collections import deque

import numpy as np
import pytest

from ompi_tpu.coll import sched
from ompi_tpu.coll.sched import NbcRequest, Round, run_blocking
from ompi_tpu.core.errors import MPIError, ERR_INTERN
from ompi_tpu.core.request import Request
from ompi_tpu.mca.var import all_pvars, all_vars, set_var
from ompi_tpu.runtime import mpool

TAG = -77
CID = 9001
# a size class nothing else in this process uses, so pool-accounting
# assertions are exact
NB = 3000
CLS = mpool.size_class(NB)


# --------------------------------------------------------- fake loopback
class _Group:
    def world_rank(self, x):
        return x


class _Router:
    def __init__(self):
        self.lock = threading.Lock()
        self.mail = {}     # (dst, src, tag, cid) -> deque[bytes]
        self.wait = {}     # (dst, src, tag, cid) -> deque[(req, view)]

    def posted(self, dst):
        with self.lock:
            return sum(len(q) for (d, *_), q in self.wait.items()
                       if d == dst)


class _FakePml:
    """Loopback pml: sends copy their payload at send time (the wire),
    recvs land in the posted view. ``fail_recv_from`` completes any
    matching recv with an error instead of data."""

    def __init__(self, router, rank, fail_recv_from=()):
        self.router = router
        self.rank = rank
        self.fail_recv_from = set(fail_recv_from)

    def isend(self, data, nbytes, dt, dst, tag, cid, qos=None):
        req = Request()
        payload = np.ascontiguousarray(data).tobytes()
        key = (dst, self.rank, tag, cid)
        deliver = None
        with self.router.lock:
            q = self.router.wait.get(key)
            if q:
                deliver = q.popleft()
            else:
                self.router.mail.setdefault(key, deque()).append(payload)
        if deliver is not None:
            rreq, view = deliver
            view[:len(payload)] = np.frombuffer(payload, np.uint8)
            rreq._set_complete(0)
        req._set_complete(0)
        return req

    def irecv(self, buf, nbytes, dt, src, tag, cid):
        req = Request()
        if src in self.fail_recv_from:
            req._set_complete(ERR_INTERN)
            return req
        view = np.asarray(buf).view(np.uint8)[:nbytes]
        key = (self.rank, src, tag, cid)
        payload = None
        with self.router.lock:
            q = self.router.mail.get(key)
            if q:
                payload = q.popleft()
            else:
                self.router.wait.setdefault(key, deque()).append(
                    (req, view))
        if payload is not None:
            view[:len(payload)] = np.frombuffer(payload, np.uint8)
            req._set_complete(0)
        return req


class _FakeComm:
    def __init__(self, router, rank, size, **pml_kw):
        self.rank = rank
        self.size = size
        self.cid = CID
        self.pml = _FakePml(router, rank, **pml_kw)
        self.group = _Group()


def _pair(**kw0):
    router = _Router()
    return _FakeComm(router, 0, 2, **kw0), _FakeComm(router, 1, 2), router


def _pool_state():
    pool = mpool.class_pool(NB)
    with pool._plock:
        return pool, pool.outstanding, len(pool._free)


# ------------------------------------------------------------- ownership
def test_pooled_recv_recycles_on_completion():
    """Clean completion returns every pooled block to its free list;
    a second identical schedule is served from the pool (hits grow)."""
    c0, c1, _ = _pair()

    def gen(comm):
        bufs = yield Round(sends=[(np.arange(NB, dtype=np.uint8), 1)],
                           recvs=[(NB, 1)])
        assert bufs[0][3] == 3

    def peer(comm):
        bufs = yield Round(sends=[(np.arange(NB, dtype=np.uint8), 0)],
                           recvs=[(NB, 0)])

    pool, out0, free0 = _pool_state()
    hits0 = sched._ctr["pool_hits"]
    t = threading.Thread(target=run_blocking,
                         args=(c1, peer(c1), TAG, CID))
    t.start()
    run_blocking(c0, gen(c0), TAG, CID)
    t.join()
    pool, out1, free1 = _pool_state()
    assert out1 == out0          # every block settled
    assert free1 >= free0 + 1    # ...by recycling, not discard
    t = threading.Thread(target=run_blocking,
                         args=(c1, peer(c1), TAG, CID))
    t.start()
    run_blocking(c0, gen(c0), TAG, CID)
    t.join()
    assert sched._ctr["pool_hits"] > hits0


def test_failed_schedule_discards_blocks_never_recycles():
    """A failing round DISCARDS its pooled blocks (the dying-conn
    lesson): outstanding settles but the free list must NOT grow."""
    c0, _, _ = _pair(fail_recv_from={1})

    def gen(comm):
        yield Round(recvs=[(NB, 1)])

    pool, out0, free0 = _pool_state()
    with pytest.raises(MPIError):
        run_blocking(c0, gen(c0), TAG, CID)
    pool, out1, free1 = _pool_state()
    assert out1 == out0
    # a block served from the free list and then discarded leaves the
    # list one SHORTER; a fresh-allocated one leaves it unchanged —
    # either way it must never grow (that would be a recycle)
    assert free1 <= free0


def test_round_free_recycles_early():
    """Round.free hands blocks back mid-schedule — the segmented ring's
    steady state: the NEXT round's alloc is a pool hit."""
    c0, c1, _ = _pair()

    def gen(comm):
        hits0 = sched._ctr["pool_hits"]
        bufs = yield Round(sends=[(np.zeros(NB, np.uint8), 1)],
                           recvs=[(NB, 1)])
        bufs2 = yield Round(sends=[(np.zeros(NB, np.uint8), 1)],
                            recvs=[(NB, 1)], free=bufs)
        assert sched._ctr["pool_hits"] > hits0

    def peer(comm):
        for _ in range(2):
            bufs = yield Round(sends=[(np.zeros(NB, np.uint8), 0)],
                               recvs=[(NB, 0)])

    t = threading.Thread(target=run_blocking,
                         args=(c1, peer(c1), TAG, CID))
    t.start()
    run_blocking(c0, gen(c0), TAG, CID)
    t.join()


def test_nbc_error_midschedule_discards_and_completes():
    """An NbcRequest whose child fails mid-schedule completes with the
    error and discards (never recycles) its pooled blocks."""
    router = _Router()
    c0 = _FakeComm(router, 0, 2, fail_recv_from={1})
    c0._nbc_seq = 0

    def gen(comm):
        # round 1: a pooled recv that will fail
        yield Round(recvs=[(NB, 1)])
        raise AssertionError("schedule must not advance past the error")

    pool, out0, free0 = _pool_state()
    req = NbcRequest(c0, gen(c0))
    with pytest.raises(MPIError):
        req.Wait()
    pool, out1, free1 = _pool_state()
    assert out1 == out0
    assert free1 <= free0  # discarded, never recycled


# ------------------------------------------------------------- windowing
def test_unordered_rounds_stay_in_flight():
    """With coll_round_window=4 the engine posts unordered rounds
    without waiting: all three recvs are live before the peer sends a
    byte. An ordered round is a barrier (lockstep fallback)."""
    c0, c1, router = _pair()
    set_var("coll_round", "window", 4)
    posted = []

    def gen(comm):
        dests = [np.zeros(64, np.uint8) for _ in range(3)]
        for i in range(3):
            yield Round(recvs=[(64, 1, dests[i])], ordered=False)
            posted.append(router.posted(0))
        yield Round(sends=[(np.zeros(0, np.uint8), 1)])  # flush marker
        for i, d in enumerate(dests):
            assert d[0] == i + 1  # results visible after the barrier

    def feeder():
        while router.posted(0) < 3:
            time.sleep(0.001)
        for i in range(3):
            c1.pml.isend(np.full(64, i + 1, np.uint8), 64, None, 0,
                         TAG, CID)

    t = threading.Thread(target=feeder)
    t.start()
    w0 = sched._ctr["windowed"]
    run_blocking(c0, gen(c0), TAG, CID)
    # drain the flush marker so the router is clean for other tests
    c1.pml.irecv(np.zeros(0, np.uint8), 0, None, 0, TAG, CID)
    t.join()
    set_var("coll_round", "window", 4)
    assert posted == [1, 2, 3]  # no barrier between unordered rounds
    assert sched._ctr["windowed"] >= w0 + 3


def test_window_one_is_lockstep():
    """window=1 restores the barrier-per-round engine: the second
    unordered round is not posted until the first completes."""
    c0, c1, router = _pair()
    set_var("coll_round", "window", 1)
    try:
        state = {"max_live": 0}

        def gen(comm):
            for i in range(3):
                yield Round(recvs=[(64, 1, np.zeros(64, np.uint8))],
                            ordered=False)
                state["max_live"] = max(state["max_live"],
                                        router.posted(0))

        def feeder():
            for _ in range(3):
                while router.posted(0) < 1:
                    time.sleep(0.001)
                c1.pml.isend(np.zeros(64, np.uint8), 64, None, 0,
                             TAG, CID)

        t = threading.Thread(target=feeder)
        t.start()
        run_blocking(c0, gen(c0), TAG, CID)
        t.join()
        assert state["max_live"] <= 1
    finally:
        set_var("coll_round", "window", 4)


def test_nbc_windowed_rounds_and_completion():
    """NbcRequest keeps unordered rounds in flight (no advance-blocking
    barrier) and completes once all of them retire."""
    c0, c1, router = _pair()
    c0._nbc_seq = 0
    set_var("coll_round", "window", 4)
    dests = [np.zeros(8, np.uint8) for _ in range(3)]

    def gen(comm):
        for i in range(3):
            yield Round(sends=[(np.full(8, i + 1, np.uint8), 1)],
                        recvs=[(8, 1, dests[i])], ordered=False)

    req = NbcRequest(c0, gen(c0))
    # the generator ran to exhaustion without any peer traffic: all
    # three rounds are posted concurrently
    assert router.posted(0) == 3
    assert not req.is_complete
    nbc_cid = CID | sched.NBC_CID_BIT
    for i in range(3):
        c1.pml.isend(np.full(8, 10 * (i + 1), np.uint8), 8, None, 0,
                     0, nbc_cid)
        c1.pml.irecv(np.zeros(8, np.uint8), 8, None, 0, 0, nbc_cid)
    req.Wait()
    for i, d in enumerate(dests):
        assert d[0] == 10 * (i + 1)


def test_nbc_empty_ordered_round_is_a_barrier():
    """A request-less ordered round (a pure drain point, e.g. one that
    only carries Round.free) must still act as a barrier in NbcRequest,
    matching run_blocking: the generator may not resume past it while
    windowed rounds are in flight."""
    c0, c1, router = _pair()
    c0._nbc_seq = 0
    set_var("coll_round", "window", 4)
    dest = np.zeros(8, np.uint8)
    resumed = []

    def gen(comm):
        yield Round(recvs=[(8, 1, dest)], ordered=False)
        yield Round()  # empty ordered round: barrier on resume
        resumed.append(dest[0])  # result must be visible here

    req = NbcRequest(c0, gen(c0))
    assert not resumed  # parked on the barrier, recv still in flight
    assert not req.is_complete
    nbc_cid = CID | sched.NBC_CID_BIT
    c1.pml.isend(np.full(8, 42, np.uint8), 8, None, 0, 0, nbc_cid)
    req.Wait()
    assert resumed == [42]


# ------------------------------------------------------- zero-copy sends
def test_contiguous_send_is_borrowed_not_copied():
    """A contiguous send payload travels as a borrowed view: the copy
    counter must not move. A strided source pays one counted copy."""
    c0, c1, router = _pair()

    def gen(comm, data):
        yield Round(sends=[(data, 1)])

    c1.pml.irecv(np.zeros(256, np.uint8), 256, None, 0, TAG, CID)
    cp0 = sched._ctr["copied"]
    run_blocking(c0, gen(c0, np.zeros(256, np.uint8)), TAG, CID)
    assert sched._ctr["copied"] == cp0
    c1.pml.irecv(np.zeros(256, np.uint8), 256, None, 0, TAG, CID)
    strided = np.zeros(512, np.uint8)[::2]
    run_blocking(c0, gen(c0, strided), TAG, CID)
    assert sched._ctr["copied"] == cp0 + 256


# ------------------------------------------------------------ legacy A/B
def test_legacy_engine_allocates_and_copies():
    """coll_round_copy_mode=1 re-materializes the legacy staging: a
    dest-view recv goes through a fresh buffer + counted postcopy."""
    c0, c1, router = _pair()
    set_var("coll_round", "copy_mode", 1)
    try:
        dest = np.zeros(128, np.uint8)

        def gen(comm):
            yield Round(recvs=[(128, 1, dest)])

        c1.pml.isend(np.full(128, 7, np.uint8), 128, None, 0, TAG, CID)
        cp0 = sched._ctr["copied"]
        h0 = sched._ctr["pool_hits"]
        run_blocking(c0, gen(c0), TAG, CID)
        assert dest[0] == 7                        # staged copy landed
        assert sched._ctr["copied"] == cp0 + 128   # ...and was counted
        assert sched._ctr["pool_hits"] == h0       # legacy never pools
    finally:
        set_var("coll_round", "copy_mode", 0)


# ----------------------------------------------------------- registration
def test_cvars_and_pvars_registered():
    vars_ = all_vars()
    for name in ("coll_round_window", "coll_round_copy_mode"):
        assert name in vars_, name
    assert vars_["coll_round_window"].default == 4
    assert vars_["coll_round_copy_mode"].default == 0
    pv = all_pvars()
    for name in ("coll_round_bytes_copied", "coll_round_bytes_moved",
                 "coll_round_pool_hits", "coll_round_windowed"):
        assert name in pv, name
        assert isinstance(pv[name].value, int)


def test_info_cli_lists_coll_round_surface(capsys):
    from ompi_tpu.tools.info import main as info_main

    info_main(["--level", "9", "--param", "coll_round", "--pvars"])
    out = capsys.readouterr().out
    assert "coll_round_window" in out
    assert "coll_round_copy_mode" in out
    assert "coll_round_bytes_copied" in out
    assert "coll_round_pool_hits" in out


# -------------------------------------------------------------- procmode
def _run_mpi(np_, mca=()):
    from tests.test_process_mode import run_mpi

    return run_mpi(np_, "tests/procmode/check_coll_round.py",
                   timeout=240,
                   mca=(("coll_coll", "^sm,adapt,han,hier,quant"),)
                   + tuple(mca))


def test_coll_round_procmode_ab_and_window():
    """End-to-end gate: >=2x copies-per-byte-moved drop vs the legacy
    engine, pool hits in steady state, windowed alltoall, and bitwise
    equality legacy == lockstep == windowed on every swept verb."""
    r = _run_mpi(4)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("COLLROUND-OK") == 4
    assert r.stdout.count("COLLROUND-EQ") == 4


def test_coll_round_chaos_delay_dup_windowed():
    """Window >1 over the real tcp wire under chaos delay+dup with idle
    parks armed: the seq gate absorbs duplicates, parks don't lose
    wakeups, results stay bitwise-correct."""
    r = _run_mpi(3, mca=(
        ("btl_btl", "^sm"),
        ("ft_inject_plan", "delay(0,1,ms=5,side=recv);dup(0,1,nth=3)"),
        ("runtime_idle_block_us", 500000)))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("COLLROUND-OK") == 3
