"""mpiracer: the static lock-discipline / cross-thread-race /
wire-protocol gate.

Tier-1 runs both passes over the whole ``ompi_tpu`` package and demands
zero findings — every cross-thread contract violation in the tree has
either been fixed, annotated (``# locked-by:`` / ``relaxed-counter``),
or carries an inline ``# mpiracer: disable=<rule> — justification``.
The self-test (one seeded-bad snippet per rule) proves every rule can
actually fire.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "ompi_tpu")
sys.path.insert(0, REPO)

from ompi_tpu.analysis import pkgmodel, protocol, threads  # noqa: E402
from ompi_tpu.analysis.report import format_finding  # noqa: E402
from tools import mpiracer  # noqa: E402


# ------------------------------------------------------------ tier-1 gate
def test_tree_clean():
    """The CI gate: zero findings from BOTH passes over the package."""
    findings = mpiracer.analyze_paths([PKG])
    assert findings == [], "\n" + "\n".join(
        format_finding(f) for f in findings)


def test_every_rule_fires_on_its_seeded_snippet():
    _findings, missed = mpiracer.self_test()
    assert missed == []


def test_rule_tables_cover_both_passes_and_common():
    assert set(mpiracer.SELF_TEST_SNIPPETS) == set(mpiracer.RULES)
    assert set(threads.RULES) <= set(mpiracer.RULES)
    assert set(protocol.RULES) <= set(mpiracer.RULES)


# ----------------------------------------------------------------- the CLI
def test_self_test_cli_exits_nonzero_on_seeded_violations():
    r = subprocess.run(
        [sys.executable, "-m", "tools.mpiracer", "--self-test"],
        cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr
    for rule in mpiracer.RULES:
        assert f"[{rule}]" in r.stderr, f"rule {rule} missing from output"


def test_cli_clean_tree_exits_zero():
    r = subprocess.run(
        [sys.executable, "-m", "tools.mpiracer", "ompi_tpu"],
        cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_cli_json_output_is_scriptable():
    r = subprocess.run(
        [sys.executable, "-m", "tools.mpiracer", "--json", "ompi_tpu"],
        cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["clean"] is True
    assert doc["findings"] == []
    tags = {t["name"]: t for t in doc["registry"]["tags"]}
    # the registry sees the whole plane space
    for name in ("REVOKE_TAG", "HEARTBEAT_TAG", "ERA_TAG", "SAN_TAG",
                 "METRICS_TAG", "FT_CKPT_TAG", "HIER_TAG", "OSC_TAG"):
        assert name in tags, sorted(tags)
        assert tags[name]["handled"], name
    assert tags["CKPT_CID_BIT"]["kind"] == "cidbit"
    # values are unique per kind once same-name re-exports (the
    # ANY_TAG package-__init__ idiom) collapse to one logical constant
    pairs = {(t["name"], t["value"])
             for t in doc["registry"]["tags"] if t["kind"] == "tag"}
    vals = [v for _n, v in pairs]
    assert len(vals) == len(set(vals))


# ------------------------------------------------------------ suppressions
def test_justified_suppression_silences_only_that_rule():
    src = (
        "import threading\n"
        "class C:\n"
        "    def lk(self):\n"
        "        with self._lock:\n"
        "            self._n = 1\n"
        "    def unl(self):\n"
        "        self._n = 2"
        "  # mpiracer: disable=lock-discipline — test fixture\n"
    )
    assert mpiracer.analyze_source(src, "ompi_tpu/coll/basic.py") == []


def test_bare_suppression_is_itself_a_finding():
    src = (
        "import threading\n"
        "class C:\n"
        "    def lk(self):\n"
        "        with self._lock:\n"
        "            self._n = 1\n"
        "    def unl(self):\n"
        "        self._n = 2  # mpiracer: disable=lock-discipline\n"
    )
    got = mpiracer.analyze_source(src, "ompi_tpu/coll/basic.py")
    assert [f.rule for f in got] == ["bare-suppression"]


def test_multi_rule_suppression_with_ascii_separator():
    """The shared pkgmodel grammar parses a two-rule list with the
    ASCII `--` separator — both rules apply, and the justification
    counts (not bare)."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def lk(self):\n"
        "        with self._lock:\n"
        "            self._n = 1\n"
        "    def unl(self):\n"
        "        self._n = 2"
        "  # mpiracer: disable=lock-discipline,cross-thread-race"
        " -- fixture\n"
    )
    assert mpiracer.analyze_source(src, "ompi_tpu/coll/basic.py") == []


def test_wrong_rule_suppression_does_not_silence():
    src = (
        "import threading\n"
        "class C:\n"
        "    def lk(self):\n"
        "        with self._lock:\n"
        "            self._n = 1\n"
        "    def unl(self):\n"
        "        self._n = 2"
        "  # mpiracer: disable=cross-thread-race — wrong rule\n"
    )
    got = mpiracer.analyze_source(src, "ompi_tpu/coll/basic.py")
    assert [f.rule for f in got] == ["lock-discipline"]


# -------------------------------------------------------- lock map / locks
def test_lock_map_inference_from_with_blocks():
    src = (
        "class C:\n"
        "    def a(self):\n"
        "        with self.engine.lock:\n"
        "            self._q[1] = 2\n"
        "    def b(self):\n"
        "        self._q.pop(1, None)\n"
    )
    got = threads.analyze_source(src, "ompi_tpu/pml/ob1.py")
    assert [f.rule for f in got] == ["lock-discipline"]
    assert "engine.lock" in got[0].message


def test_init_writes_neither_infer_nor_flag():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"       # ctor write: no inference
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
    )
    assert threads.analyze_source(src, "ompi_tpu/pml/ob1.py") == []


def test_locked_by_annotation_on_attribute_definition():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0  # locked-by: self._lock\n"
        "    def a(self):\n"
        "        self._n = 5\n"
    )
    got = threads.analyze_source(src, "ompi_tpu/pml/ob1.py")
    assert [f.rule for f in got] == ["lock-discipline"]


def test_locked_by_annotation_on_def_asserts_caller_holds():
    src = (
        "import threading\n"
        "class C:\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n = 1\n"
        "    def b(self):  # locked-by: self._lock\n"
        "        self._n = 2\n"
    )
    assert threads.analyze_source(src, "ompi_tpu/pml/ob1.py") == []


def test_condition_context_counts_as_lock():
    src = (
        "class C:\n"
        "    def a(self):\n"
        "        with self._cond:\n"
        "            self._n = 1\n"
        "    def b(self):\n"
        "        with self._cond:\n"
        "            self._n = 2\n"
    )
    assert threads.analyze_source(src, "ompi_tpu/pml/ob1.py") == []


def test_relaxed_counter_marker_exempts_with_justification():
    base = (
        "from ompi_tpu.runtime.progress import register_progress\n"
        "_ctr = [0]{marker}\n"
        "def Send(x):\n"
        "    _ctr[0] += 1\n"
        "def _cb():\n"
        "    _ctr[0] += 1\n"
        "    return 0\n"
        "register_progress(_cb)\n"
    )
    ok = base.format(
        marker="  # mpiracer: relaxed-counter — loss tolerated")
    assert threads.analyze_source(
        ok, "ompi_tpu/comm/communicator.py") == []
    # without a justification the marker is ignored and the race fires
    bare = base.format(marker="  # mpiracer: relaxed-counter")
    got = threads.analyze_source(bare, "ompi_tpu/comm/communicator.py")
    assert {f.rule for f in got} == {"cross-thread-race"}


# ------------------------------------------------- thread reachability
def test_call_graph_labels_app_progress_and_dual():
    src = (
        "from ompi_tpu.runtime.progress import register_progress\n"
        "class Comm:\n"
        "    def Send(self, x):\n"
        "        self._shared()\n"
        "    def _app_only(self):\n"
        "        pass\n"
        "    def _shared(self):\n"
        "        pass\n"
        "    def _drain(self):\n"
        "        self._shared()\n"
        "        return 0\n"
        "def install(c):\n"
        "    register_progress(c._drain)\n"
    )
    model = threads.build_model(
        pkgmodel.load_source(src, "ompi_tpu/comm/communicator.py"))
    labels = {f.name: f.label for f in model.fns.values()}
    assert labels["Send"] == threads.APP
    assert labels["_drain"] & threads.PROG
    assert labels["_shared"] == threads.APP | threads.PROG
    assert labels["_app_only"] == 0  # defined, never reached


def test_thread_target_and_system_handler_seed_progress():
    src = (
        "import threading\n"
        "class HB:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "    def _run(self):\n"
        "        pass\n"
        "def bind(pml):\n"
        "    pml.register_system_handler(-4999, _on_msg)\n"
        "def _on_msg(hdr, payload):\n"
        "    pass\n"
    )
    model = threads.build_model(
        pkgmodel.load_source(src, "ompi_tpu/ft/detector.py"))
    labels = {f.name: f.label for f in model.fns.values()}
    assert labels["_run"] & threads.PROG
    assert labels["_on_msg"] & threads.PROG


# -------------------------------------------------------- protocol pass
def test_tag_collision_unit_and_same_name_reexport_exempt():
    src = "A_TAG = -4650\nB_TAG = -4650\n"
    got = protocol.analyze_source(src, "ompi_tpu/ft/x.py")
    assert any(f.rule == "tag-collision" for f in got)
    # the same name re-declared (package __init__ re-export idiom)
    reexport = "ANY_TAG = -1\n"
    pkg_src = {"ompi_tpu/pml/base.py": reexport,
               "ompi_tpu/__init__.py": reexport}
    mods = [pkgmodel.ModuleInfo(p, s) for p, s in pkg_src.items()]
    got = protocol.check_registry(
        pkgmodel.Package(mods), protocol.build_registry(
            pkgmodel.Package(mods)))
    assert not any(f.rule == "tag-collision" for f in got)


def test_orphan_tag_fires_only_below_system_base():
    sent_sys = (
        "from ompi_tpu.pml.base import send_system\n"
        "X_TAG = -4650\n"
        "def ship(pml):\n"
        "    send_system(pml, 0, {}, X_TAG)\n"
    )
    got = protocol.analyze_source(sent_sys, "ompi_tpu/ft/x.py")
    assert any(f.rule == "orphan-tag" for f in got)
    # a collective-plane tag (> -4000) is matched, not dispatched
    sent_coll = (
        "TAG_X = -35\n"
        "def go(pml):\n"
        "    pml.isend(b'', 0, None, 1, TAG_X, 0)\n"
    )
    got = protocol.analyze_source(sent_coll, "ompi_tpu/coll/x.py")
    assert not any(f.rule == "orphan-tag" for f in got)


def _fence_pkg(tmp_path, bind_before_fence: bool):
    root = tmp_path / "ompi_tpu"
    (root / "runtime").mkdir(parents=True)
    (root / "ft").mkdir()
    bind = "    ftx.bind_plane(pml)\n"
    wireup = (
        "def init_process_mode():\n"
        "    from ompi_tpu.ft import x as ftx\n"
        "    pml = make_pml()\n"
        "    modex.fence()\n"
        + (bind if bind_before_fence else "")
        + "    modex.fence()\n"
        + ("" if bind_before_fence else bind)
        + "    return pml\n"
    )
    plane = (
        "from ompi_tpu.pml.base import SystemPlane\n"
        "X_TAG = -4650\n"
        "def _on(hdr, payload):\n"
        "    pass\n"
        "_plane = SystemPlane(X_TAG, _on)\n"
        "def bind_plane(pml):\n"
        "    _plane.ensure(pml)\n"
    )
    (root / "runtime" / "wireup.py").write_text(wireup)
    (root / "ft" / "x.py").write_text(plane)
    return pkgmodel.load_package([str(root)])


def test_handler_fence_passes_prefence_binding(tmp_path):
    pkg = _fence_pkg(tmp_path, bind_before_fence=True)
    got = protocol.analyze_package(pkg)
    assert not any(f.rule == "handler-fence" for f in got), got


def test_handler_fence_fires_on_postfence_binding(tmp_path):
    pkg = _fence_pkg(tmp_path, bind_before_fence=False)
    got = protocol.analyze_package(pkg)
    assert any(f.rule == "handler-fence" for f in got)


# --------------------------------------- regressions for the real fixes
def test_diag_planes_bound_prefence_in_real_tree():
    """PR 13 fix: the sanitizer (-4400), metrics (-4500), and hier
    retune (-4700) planes were bound only by init_bottom hooks /
    first-use lazily — AFTER the wireup pre-activation fence, so a fast
    peer's first frame could be dropped (the PR 5 diskless flake
    class). They now bind from wireup like diskless; the fence pass
    over the real tree must stay clean for them."""
    got = protocol.analyze_paths([PKG])
    fence = [f for f in got if f.rule == "handler-fence"]
    assert fence == [], "\n".join(format_finding(f) for f in fence)
    src = open(os.path.join(PKG, "runtime", "wireup.py")).read()
    pre = src.split("connect_parent_if_spawned")[0]
    for call in ("rt_sanitizer.bind_plane(pml)",
                 "rt_metrics.bind_plane(pml)",
                 "hier_decide.bind_plane(pml)"):
        assert call in pre, call


def test_metrics_bind_plane_binds_when_enabled():
    from ompi_tpu.mca.var import set_var
    from ompi_tpu.runtime import metrics

    class FakePml:
        def __init__(self):
            self.handlers = {}

        def register_system_handler(self, tag, fn):
            self.handlers[tag] = fn

    old = metrics._enable_var._value
    try:
        p = FakePml()
        set_var("metrics", "enable", False)
        metrics.bind_plane(p)
        assert metrics.METRICS_TAG not in p.handlers
        set_var("metrics", "enable", True)
        metrics.bind_plane(p)
        assert metrics.METRICS_TAG in p.handlers
    finally:
        set_var("metrics", "enable", old)
        metrics._plane.reset()


def test_hier_bind_plane_is_unconditional():
    from ompi_tpu.coll.hier import decide

    class FakePml:
        def __init__(self):
            self.handlers = {}

        def register_system_handler(self, tag, fn):
            self.handlers[tag] = fn

    p = FakePml()
    try:
        decide.bind_plane(p)
        assert decide.HIER_TAG in p.handlers
    finally:
        decide._plane.reset()


def test_idle_blocks_pvar_bump_is_locked_and_counts():
    """PR 13 fix: the progress_idle_blocks bump was an unlocked += on a
    module global hit by both the app thread (progress_until) and the
    ProgressThread — the _call_count bug class. It now runs under
    _wake_lock; a completed park must still count exactly once."""
    from ompi_tpu.runtime import progress

    old_sources = list(progress._idle_sources)
    progress.set_idle_sources([])  # fd-complete (empty): parking allowed
    try:
        before = progress._idle_blocks[0]
        parked = progress.idle_block(0.01, 0.001)
        assert parked is True
        assert progress._idle_blocks[0] == before + 1
    finally:
        progress.set_idle_sources(old_sources)
    # and the tree gate agrees: no cross-thread finding in progress.py
    got = threads.analyze_paths(
        [os.path.join(PKG, "runtime", "progress.py")])
    assert not any(f.rule == "cross-thread-race" for f in got), got


def test_serve_surface_and_daemon_entries_are_seeded():
    """PR 17 fix: the PR 15 serving stack was invisible to the thread
    reachability pass — serve/* was in no entry list, and the qos
    storm/sink daemon threads enter the package through the PRIVATE
    ft/diskless._ship (private names are never APP-seeded), so none of
    the state they touch was race-checked. serve/* now seeds APP and
    _ship is a curated daemon (PROG) entry. TrafficGen.run stays
    app-only ON PURPOSE: the harness and the procmode checks call
    gen.run(...) inline on the main thread — only the storm/sink
    closures around it are daemons — and PROG-seeding it would falsely
    dual-label the whole collective stack it drives."""
    for relp in ("serve/harness.py", "serve/traffic.py",
                 "serve/churn.py"):
        assert relp in threads.APP_ENTRY_MODULES, relp
    assert ("ft/diskless.py", None, "_ship") in threads.DAEMON_ENTRY_FNS
    model = threads.build_model(pkgmodel.load_package([PKG]))
    threads._seed_and_propagate(model)
    ship = model.fns["ft/diskless.py::_ship"]
    assert ship.label & threads.PROG          # the daemon side
    assert ship.label & threads.APP           # commit/save app callers
    run = model.fns["serve/traffic.py::TrafficGen.run"]
    assert run.label & threads.APP
    assert not run.label & threads.PROG       # main-thread caller only
    su = model.fns["serve/harness.py::ServingHarness.serve_until"]
    assert su.label & threads.APP


def test_daemon_entry_convention_is_class_scoped(monkeypatch):
    """A (module, None, name) daemon entry matches the module-level
    function only — a same-named method is untouched (and vice versa),
    so a generic name cannot be seeded package-wide."""
    src = (
        "class A:\n"
        "    def go(self):\n"
        "        pass\n"
        "def go():\n"
        "    pass\n"
    )
    monkeypatch.setattr(threads, "DAEMON_ENTRY_FNS",
                        (("ft/x.py", None, "go"),))
    model = threads.build_model(
        pkgmodel.load_source(src, "ompi_tpu/ft/x.py"))
    threads._seed_and_propagate(model)
    labels = {f.qual: f.label for f in model.fns.values()}
    assert labels["ft/x.py::go"] & threads.PROG
    assert not labels["ft/x.py::A.go"] & threads.PROG


def test_qos_cache_invalidation_rebinds_atomically():
    """PR 13 fix: _clear_cache() used dict.clear(), which racing a
    concurrent classify() insert could resurrect a stale class after a
    comm-attr rewrite. It now swaps in a fresh dict (one atomic
    store)."""
    from ompi_tpu import qos

    qos._cls_cache[123] = qos.BULK
    old = qos._cls_cache
    qos._clear_cache()
    assert qos._cls_cache is not old          # rebound, not cleared
    assert qos._cls_cache == {}
    assert old[123] == qos.BULK               # in-flight readers intact
    # and the lookup binds the dict ONCE: a stale insert racing the
    # rebind must land in the DISCARDED dict, never the fresh one —
    # else a pre-invalidation class resurrects onto a recycled cid
    import ast
    import inspect

    tree = ast.parse(inspect.getsource(qos._comm_class))
    global_reads = [n.lineno for n in ast.walk(tree)
                    if isinstance(n, ast.Name) and n.id == "_cls_cache"]
    assert len(global_reads) == 1, (
        "_comm_class must read the module global exactly once "
        f"(got lines {global_reads})")
