"""Observability: SPC counters, pvars, the info CLI, comm_method hook.

Reference: ompi/runtime/ompi_spc.c (counters + MPI_T pvar export),
opal/mca/base/mca_base_pvar.c, ompi/tools/ompi_info,
ompi/mca/hook/comm_method.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu import COMM_WORLD
from ompi_tpu.runtime import spc
from tests.test_process_mode import REPO, run_mpi, subprocess_env


def test_spc_records_collectives_and_bytes():
    spc.reset()
    before = spc.get("allreduce")
    out = np.zeros(4, np.float32)
    COMM_WORLD.Allreduce(np.ones(4, np.float32), out)
    assert spc.get("allreduce") == before + 1

    COMM_WORLD.Send(np.zeros(8, np.float64), dest=0, tag=50)
    got = np.zeros(8, np.float64)
    COMM_WORLD.Recv(got, source=0, tag=50)
    assert spc.get("send_count") >= 1
    assert spc.get("send_bytes") >= 64
    assert spc.get("recv_bytes") >= 64


def test_spc_timer_and_dump(capsys):
    spc.reset()
    with spc.timer("unit_test_op"):
        pass
    snap = spc.snapshot()
    assert "unit_test_op_time_us" in snap
    spc.dump(file=sys.stdout)
    assert "unit_test_op_time_us" in capsys.readouterr().out


def test_spc_disable():
    from ompi_tpu.mca.var import set_var

    spc.reset()
    set_var("spc", "enable", False)  # must take effect immediately
    try:
        spc.record("should_not_appear")
        assert spc.get("should_not_appear") == 0
    finally:
        set_var("spc", "enable", True)
    spc.record("reappears")
    assert spc.get("reappears") == 1
    # internal-traffic suppression (library calls must not pollute
    # user-facing counters)
    with spc.suppressed():
        spc.record("internal_only")
    assert spc.get("internal_only") == 0


def test_spc_timer_reentrant():
    """Nested use of ONE timer instance (recursive call sites) must
    accumulate per level — the old single-slot _t0 let the inner enter
    clobber the outer's baseline, losing the outer's elapsed time."""
    import time

    spc.reset()
    t = spc.timer("nest")
    with t:
        with t:
            time.sleep(0.002)
    assert t._starts == []  # balanced
    # inner >= 2ms and outer >= 2ms (it contains the inner), so the
    # accumulated total must show BOTH levels, not just one
    assert spc.get("nest_time_us") >= 3600


def test_monitoring_pvar_rebinds_reader():
    """register_pvar dedupes by name; a second MonitoringPml must rebind
    the pvar readers to itself or the pvars silently keep reporting the
    dead first instance's counters."""
    from ompi_tpu.mca.var import all_pvars
    from ompi_tpu.pml.monitoring import MonitoringPml

    class _FakePml:
        my_rank = 0

    m1 = MonitoringPml(_FakePml())
    m1._bump(1, "tx", 100)
    assert all_pvars()["pml_monitoring_total_sent_bytes"].value == 100
    m2 = MonitoringPml(_FakePml())  # re-registration
    assert all_pvars()["pml_monitoring_total_sent_bytes"].value == 0
    m2._bump(2, "tx", 7)
    m2._bump(1, "rx", 3)
    assert all_pvars()["pml_monitoring_total_sent_bytes"].value == 7
    assert all_pvars()["pml_monitoring_total_recv_bytes"].value == 3


def test_pvars_surface_spc_counters():
    from ompi_tpu.mca.var import all_pvars

    spc.reset()
    out = np.zeros(1, np.float32)
    COMM_WORLD.Allreduce(np.ones(1, np.float32), out)
    pvars = all_pvars()
    assert "spc_allreduce" in pvars
    assert pvars["spc_allreduce"].value >= 1


def test_info_cli():
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.info", "--all"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env=subprocess_env())
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "frameworks / components" in out
    # every framework with its components
    for frag in ("btl", "coll", "accelerator",
                 "xla (priority 100)", "sm (priority 30)",
                 "tcp (priority 20)", "tpu (priority 50)"):
        assert frag in out, frag
    # vars with metadata
    assert "btl_sm_ring_bytes" in out
    assert "source default" in out
    assert "performance variables" in out


def test_info_cli_param_filter():
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.info", "--param", "spc",
         "--level", "9"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env=subprocess_env())
    assert r.returncode == 0, r.stderr
    assert "spc_enable" in r.stdout
    assert "btl_sm_ring_bytes" not in r.stdout


def test_comm_method_hook_procmode():
    r = run_mpi(2, "examples/ring.py", mca=(("hook_comm_method", "1"),))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "comm_method rank 0:" in r.stderr
    assert "sm" in r.stderr or "tcp" in r.stderr


def test_internal_collectives_not_counted():
    """Dup/Split CID agreement and window barriers are library-internal
    traffic; counters must reflect user activity only (r2 review)."""
    spc.reset()
    dup = COMM_WORLD.Dup()
    assert spc.get("allreduce") == 0  # CID agreement suppressed
    dup.Free()


def test_failed_send_not_counted():
    spc.reset()
    with pytest.raises(ompi_tpu.MPIError):
        COMM_WORLD.Send(np.zeros(2, np.float32), dest=5)
    assert spc.get("send_count") == 0


def test_registered_pvars():
    from ompi_tpu.mca.var import all_pvars

    pv = all_pvars()
    assert "pml_unexpected_queue_length" in pv
    assert pv["pml_unexpected_queue_length"].value >= 0


def test_peruse_events():
    """PERUSE-style request-lifecycle events (reference: ompi/peruse,
    hooks at pml_ob1_isend.c:321)."""
    from ompi_tpu.runtime import peruse

    seen = []
    fn = lambda ev, info: seen.append(ev)
    peruse.subscribe("send_posted", fn)
    peruse.subscribe("recv_posted", fn)
    peruse.subscribe("request_complete", fn)
    try:
        buf = np.zeros(2, np.float64)
        COMM_WORLD.Send(np.ones(2), dest=0, tag=77)
        COMM_WORLD.Recv(buf, source=0, tag=77)
        assert "send_posted" in seen
        assert "recv_posted" in seen
        assert seen.count("request_complete") >= 2
    finally:
        for ev in ("send_posted", "recv_posted", "request_complete"):
            peruse.unsubscribe(ev, fn)
    assert not peruse.enabled
