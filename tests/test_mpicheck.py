"""mpicheck: the umbrella runner over every static gate.

Tier-1 keeps the individual gates (test_mpilint / test_mpiracer /
test_mpiown / the trace-schema checks); this file covers only the
umbrella's own contracts — check routing, the --fast subset, the
merged JSON shape, and the worst-of exit code.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import mpicheck  # noqa: E402


def _run(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.mpicheck", *args],
        cwd=cwd, capture_output=True, text=True)


def test_full_run_is_clean_and_covers_every_tree_gate():
    r = _run()
    assert r.returncode == 0, r.stdout + r.stderr
    for name in ("mpilint", "mpiracer", "mpiown"):
        assert f"{name}: OK" in r.stdout, r.stdout
    # no trace args -> no trace_lint line
    assert "trace_lint" not in r.stdout


def test_fast_subset_skips_the_call_graph_pass():
    r = _run("--fast")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "mpilint: OK" in r.stdout
    assert "mpiown: OK" in r.stdout
    assert "mpiracer" not in r.stdout


def test_json_args_route_to_trace_lint(tmp_path):
    bad = tmp_path / "trace.json"
    bad.write_text(json.dumps({"traceEvents": [
        {"ph": "B", "name": "x", "ts": 0, "pid": 1, "tid": 1},
    ]}))  # B never closed: a trace-schema finding
    r = _run("--fast", str(bad))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "trace_lint:" in r.stderr
    assert "[trace-schema]" in r.stderr
    # the tree gates still ran and stayed clean
    assert "mpilint: OK" in r.stdout


def test_merged_json_doc_keys_findings_by_check(tmp_path):
    bad = tmp_path / "trace.json"
    bad.write_text("not json at all")
    r = _run("--fast", "--json", str(bad))
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert doc["clean"] is False
    assert set(doc["checks"]) == {"mpilint", "mpiown", "trace_lint"}
    assert doc["checks"]["mpilint"]["clean"] is True
    assert doc["checks"]["trace_lint"]["clean"] is False
    # the flattened list carries the originating check per finding
    assert any(f["check"] == "trace_lint" for f in doc["findings"])


def test_worst_of_exit_code_over_a_dirty_tree(tmp_path):
    pkg = tmp_path / "ompi_tpu" / "btl"
    pkg.mkdir(parents=True)
    (pkg / "x.py").write_text(
        "def go(pool):\n    block = pool.acquire()\n")  # mpiown leak
    r = _run("--fast", str(tmp_path / "ompi_tpu"))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "[pool-leak]" in r.stderr
    assert "mpilint: OK" in r.stdout  # the clean gates still report OK


def test_missing_path_is_a_usage_error():
    r = _run("no/such/dir")
    assert r.returncode == 2


def test_run_checks_api_orders_and_labels():
    checks = mpicheck.run_checks(
        [os.path.join(REPO, "ompi_tpu")], [], fast=True)
    assert sorted(checks) == ["mpilint", "mpiown"]
    assert all(fs == [] for fs in checks.values())
