"""SLO-driven autoscaling with brownout load shedding (serve/autoscale).

Unit coverage for the pure pieces — the hysteretic ScalePolicy, the
BrownoutLadder shed precedence (BULK first, then NORMAL, never
LATENCY; latched with staged re-arm) and the Autoscaler's brownout
causes (spawn budget, RTO budget) against a fake harness — plus the
admission-gate resize-while-queued contract, the promexport grammar
check over the new metrics surface, the mpitop WORLD/SHED cells, the
registration/info surface, and the two procmode proofs:

- check_autoscale.py 'scenario': one run drives closed-form traffic
  through grow -> steady -> flash-crowd brownout -> shrink with the
  world size DECIDED by the controller, bitwise-exact state after
  every resize (the ISSUE 20 acceptance run).
- check_spawn_retry.py 'parent': dpm.spawn survives a transient child
  death via the bounded backoff retry and still raises ERR_SPAWN when
  a persistent failure exhausts the budget.
"""

import os
import re
import subprocess
import sys
import threading
import time

import pytest

import ompi_tpu.serve  # noqa: F401  registers the serve_* surface
from ompi_tpu.core.errors import MPIError, ERR_SPAWN
from ompi_tpu.mca.var import all_pvars, all_vars, get_var, set_var
from ompi_tpu.runtime import metrics
from ompi_tpu.serve import autoscale as sauto
from ompi_tpu.serve import policy as spolicy
from ompi_tpu.serve import slo as sslo
from ompi_tpu.serve import traffic as straffic
from ompi_tpu.serve.autoscale import (
    Autoscaler,
    BrownoutLadder,
    ScalePolicy,
    Signals,
)
from ompi_tpu.serve.policy import AdmissionGate

from tests.test_process_mode import REPO
from tests.test_serve import FT_SERVE, _FakeComm, _blame, run_mpi

sys.path.insert(0, os.path.join(REPO, "tools"))

import mpitop  # noqa: E402
import promexport  # noqa: E402

pv = all_pvars()


@pytest.fixture(autouse=True)
def clean_autoscale():
    yield
    sauto.reset_for_testing()
    sslo.reset_for_testing()
    straffic.reset_for_testing()
    spolicy.reset_for_testing()
    metrics.reset_for_testing()


@pytest.fixture
def restore_vars():
    saved = {}

    def save(fw, name):
        saved[(fw, name)] = get_var(fw, name)

    yield save
    for (fw, name), v in saved.items():
        set_var(fw, name, v)


@pytest.fixture
def no_failures(monkeypatch):
    from ompi_tpu.ft import detector

    monkeypatch.setattr(detector, "known_failed", lambda: set())


def _policy(**kw):
    kw.setdefault("min_world", 1)
    kw.setdefault("max_world", 8)
    kw.setdefault("up_util", 0.8)
    kw.setdefault("down_util", 0.5)
    kw.setdefault("up_cooldown", 4)
    kw.setdefault("down_cooldown", 8)
    kw.setdefault("max_step", 1)
    kw.setdefault("queue_high", 4)
    kw.setdefault("headroom_min", 0.1)
    return ScalePolicy(**kw)


# ------------------------------------------------------------- policy
def test_policy_asymmetric_band_holds_flat_load():
    """Demand inside (down, up) thresholds is a hold in BOTH
    directions — the hysteresis band that keeps flat load from
    flapping the world size."""
    p = _policy()
    # up edge: 3 * 0.8 = 2.4; down edge: (3-1) * 0.5 = 1.0
    assert p.decide(3, Signals(2.2), 0) == (3, None)
    assert p.decide(3, Signals(1.0), 0) == (3, None)   # at the edge
    assert p.decide(3, Signals(2.5), 0) == (4, "arrival")
    assert _policy().decide(3, Signals(0.9), 100) == (2, "idle")


def test_policy_per_direction_cooldowns():
    p = _policy(up_cooldown=4, down_cooldown=8)
    assert p.decide(2, Signals(5.0), 0) == (3, "arrival")
    assert p.decide(3, Signals(5.0), 2) == (3, None)    # up cooling
    assert p.decide(3, Signals(5.0), 4)[1] == "arrival"
    q = _policy(down_cooldown=8)
    assert q.decide(4, Signals(0.1), 0) == (3, "idle")
    assert q.decide(3, Signals(0.1), 4) == (3, None)    # down cooling
    assert q.decide(3, Signals(0.1), 8) == (2, "idle")
    # the cooldowns are per direction: an up right after a down is
    # legal (load came back — do not sit on the floor for 8 steps)
    assert q.decide(2, Signals(9.0), 9)[1] == "arrival"


def test_policy_min_max_clamps():
    p = _policy(min_world=2, max_world=3)
    assert p.decide(3, Signals(9.0), 0) == (3, None)    # at the ceiling
    assert p.decide(2, Signals(0.0), 0) == (2, None)    # at the floor
    assert p.overloaded(3, Signals(9.0))
    assert not p.overloaded(2, Signals(9.0))            # can still grow
    assert not p.overloaded(3, Signals(1.0))            # no pressure
    # max_world 0 (the cvar default) means unbounded
    assert ScalePolicy(max_world=0).max_world() > 1 << 20


def test_policy_bounded_step_and_demand_need():
    # need = ceil(demand / up_util) ranks; the step bound clamps it
    p = _policy(up_util=1.0, max_step=2)
    assert p.decide(1, Signals(10.0), 0) == (3, "arrival")
    q = _policy(up_util=1.0, max_step=16, max_world=32)
    assert q.decide(1, Signals(10.0), 0) == (10, "arrival")
    # ...and the world ceiling clamps the need
    assert _policy(up_util=1.0, max_step=16).decide(
        1, Signals(10.0), 0) == (8, "arrival")
    # a non-arrival trigger with no demand magnitude asks for ONE rank
    r = _policy(queue_high=4)
    assert r.decide(2, Signals(0.0, queue_depth=9.0), 0) == (3, "queue")


def test_policy_scale_down_is_always_one_rank():
    """Regardless of max_step: retiring a block of top ranks can
    retire a rank together with every buddy replica of its state."""
    p = _policy(max_step=4)
    assert p.decide(5, Signals(0.0), 0) == (4, "idle")


def test_policy_trigger_class_precedence():
    sig = Signals(9.0, queue_depth=9.0, slo_headroom=-1.0)
    assert _policy().decide(2, sig, 0)[1] == "arrival"
    sig = Signals(0.0, queue_depth=9.0, slo_headroom=-1.0)
    assert _policy().decide(2, sig, 0)[1] == "queue"
    sig = Signals(0.0, queue_depth=0.0, slo_headroom=0.05)
    assert _policy().decide(2, sig, 0)[1] == "slo"


# ------------------------------------------------------------- ladder
def test_ladder_sheds_bulk_first_then_normal_never_latency():
    lad = BrownoutLadder(rearm_evals=1)
    assert lad.note_eval(True) == "shed:bulk"
    assert lad.shed == {"bulk"} and lad.latched
    assert not lad.should_shed("normal")
    assert lad.note_eval(True) == "shed:normal"
    assert lad.shed == {"bulk", "normal"}
    assert lad.note_eval(True) is None          # fully escalated
    # LATENCY is structurally uncheddable: not a rung at all
    assert "latency" not in BrownoutLadder.RUNGS
    assert not lad.should_shed("latency")


def test_ladder_staged_rearm_restores_normal_before_bulk():
    lad = BrownoutLadder(rearm_evals=2)
    lad.note_eval(True)
    lad.note_eval(True)
    assert lad.note_eval(False) is None         # calm 1 of 2
    assert lad.note_eval(False) == "restore:normal"
    assert lad.shed == {"bulk"} and lad.latched
    assert lad.note_eval(False) is None
    assert lad.note_eval(False) == "restore:bulk:disarm"
    assert lad.shed == set() and not lad.latched
    assert lad.note_eval(False) is None         # disarmed: inert


def test_ladder_overload_resets_the_calm_streak():
    lad = BrownoutLadder(rearm_evals=2)
    lad.note_eval(True)
    assert lad.note_eval(False) is None          # calm 1 of 2
    assert lad.note_eval(True) == "shed:normal"  # relapse re-escalates
    assert lad.note_eval(False) is None          # streak restarted
    assert lad.note_eval(False) == "restore:normal"


# --------------------------------------------------------- controller
class _Harness:
    """The minimum surface the Autoscaler steers: an admission gate
    holding the live comm, the traffic seed, and the resize-adoption
    seam (recorded, not executed)."""

    def __init__(self, ranks=(0, 1, 2), seed=3):
        self.gate = AdmissionGate(_FakeComm(ranks=ranks))
        self.seed = seed
        self.state = {}
        self.scaler = None
        self.step = 0
        self.adopted = []

    def attach_autoscaler(self, scaler):
        self.scaler = scaler

    def state_step(self):
        return self.step

    def adopt_resize(self, comm, state=None):
        self.adopted.append((comm, state))
        self.gate.install(comm)
        self.gate.full_size = comm.Get_size()


def test_autoscaler_shed_sequence_is_deterministic(restore_vars):
    """During a full shed the applied arrival is ALWAYS latency-class:
    the (step, attempt) class walk strides every pattern slot, and the
    shed counters advance identically on a rebuilt controller."""
    restore_vars("serve", "autoscale_eval_steps")
    set_var("serve", "autoscale_eval_steps", 0)   # policy eval off

    def drive(step):
        h = _Harness(seed=3)
        sc = Autoscaler(h, lambda s: 0.0)
        sc.mode = "brownout"
        sc.ladder.latched = True
        sc.ladder.shed = {"bulk", "normal"}
        h.step = step
        verdicts = []
        for _ in range(16):
            ok = sc.before_step(h)
            verdicts.append((ok, sc.last_class()))
            if ok:
                sc.note_step_applied(step)
                break
        return verdicts

    b0 = pv["serve_shed_steps_bulk"].value
    n0 = pv["serve_shed_steps_normal"].value
    got = drive(14)
    # seed 3, step 14: the walk hits normal, normal, normal, latency
    assert [c for _, c in got] == ["normal", "normal", "normal",
                                  "latency"]
    assert [ok for ok, _ in got] == [False, False, False, True]
    assert got[-1] == (True, "latency")           # latency is served
    assert pv["serve_shed_steps_normal"].value == n0 + 3
    assert pv["serve_shed_steps_bulk"].value == b0
    assert drive(14) == got                       # bitwise rerun
    # a partial shed set passes the first non-shed class straight through
    h = _Harness(seed=3)
    sc = Autoscaler(h, lambda s: 0.0)
    sc.mode = "brownout"
    sc.ladder.latched = True
    sc.ladder.shed = {"bulk"}
    h.step = 14
    assert sc.before_step(h) and sc.last_class() == "normal"


def test_autoscaler_spawn_budget_exhaustion_latches_brownout(
        restore_vars, monkeypatch):
    """ERR_SPAWN after dpm's bounded retry must NOT spin the scale-up:
    the RTO clock is cancelled (no bogus sample) and brownout latches
    with cause spawn_budget."""
    from ompi_tpu.ft import recovery as _recovery

    restore_vars("serve", "autoscale_eval_steps")
    set_var("serve", "autoscale_eval_steps", 2)

    def boom(*a, **kw):
        raise MPIError(ERR_SPAWN, "child died before wireup")

    monkeypatch.setattr(_recovery, "grow", boom)
    h = _Harness(ranks=(0, 1))
    sc = Autoscaler(h, lambda s: 9.0, policy=_policy(max_world=8))
    before = pv["serve_autoscale_brownouts"].value
    ups = pv["serve_autoscale_scale_ups"].value
    assert sc.before_step(h)                     # eval fires at step 0
    assert sc.mode == "brownout"
    assert sc.brownout_cause == "spawn_budget"
    assert sc.ladder.shed == {"bulk"}
    assert pv["serve_autoscale_brownouts"].value == before + 1
    assert pv["serve_autoscale_scale_ups"].value == ups + 1
    assert not sc.rto.running("arrival")         # cancelled, not stopped
    assert sc._pending_rto is None
    assert h.adopted == []                       # the world never changed
    # a real (non-spawn) failure during grow must still propagate
    monkeypatch.setattr(
        _recovery, "grow",
        lambda *a, **kw: (_ for _ in ()).throw(MPIError(1, "other")))
    h2 = _Harness(ranks=(0, 1))
    sc2 = Autoscaler(h2, lambda s: 9.0, policy=_policy(max_world=8))
    with pytest.raises(MPIError):
        sc2.before_step(h2)


def test_autoscaler_rto_budget_blown_latches_brownout(restore_vars):
    """A measured resize RTO above serve_autoscale_rto_budget_ms
    journals at completion and latches brownout at the NEXT eval."""
    restore_vars("serve", "autoscale_eval_steps")
    restore_vars("serve", "autoscale_rto_budget_ms")
    set_var("serve", "autoscale_eval_steps", 2)
    set_var("serve", "autoscale_rto_budget_ms", 0.001)
    h = _Harness(ranks=(0, 1, 2))
    # calm signal: no up pressure, no down (demand inside the band)
    sc = Autoscaler(h, lambda s: 1.5, policy=_policy(max_world=3))
    sc.mode = "scaling"
    sc.rto.start("arrival")
    sc._pending_rto = "arrival"
    time.sleep(0.001)
    sc.note_step_applied(1)
    assert sc.mode == "armed"                    # resize settled...
    assert sc._rto_blown == "arrival"            # ...but over budget
    h.step = 2
    sc.before_step(h)                            # next eval latches
    assert sc.mode == "brownout"
    assert sc.brownout_cause == "rto_budget"
    assert sc._rto_blown is None                 # consumed


def test_autoscaler_brownout_rearm_returns_to_armed(restore_vars):
    restore_vars("serve", "autoscale_eval_steps")
    set_var("serve", "autoscale_eval_steps", 2)
    h = _Harness(ranks=(0, 1, 2))
    demand = {"v": 9.0}
    sc = Autoscaler(h, lambda s: demand["v"],
                    policy=_policy(max_world=3),
                    ladder=BrownoutLadder(rearm_evals=1))
    h.step = 0
    sc.before_step(h)                            # overloaded at ceiling
    assert sc.mode == "brownout"
    assert sc.brownout_cause == "max_world"
    h.step = 2
    sc.before_step(h)                            # still hot: sheds NORMAL
    assert sc.ladder.shed == {"bulk", "normal"}
    demand["v"] = 1.5                            # calm, inside the band
    h.step = 4
    sc.before_step(h)                            # restore:normal
    assert sc.mode == "brownout"                 # bulk still shed
    h.step = 6
    sc.before_step(h)                            # restore:bulk:disarm
    assert sc.mode == "armed"
    assert sc.brownout_cause is None
    assert not sc.ladder.latched


def test_autoscaler_resize_note_roundtrip():
    h = _Harness()
    sc = Autoscaler(h, lambda s: 0.0, policy=_policy())
    sc.policy.last_up = 4
    sc._last_eval = 4
    note = sc.resize_note()
    assert note == {"last_up": 4, "last_down": None, "last_eval": 4}
    h2 = _Harness()
    sc2 = Autoscaler(h2, lambda s: 0.0, policy=_policy())
    sc2.apply_note(note)
    assert sc2.policy.last_up == 4
    assert sc2.policy.last_down is None
    assert sc2._last_eval == 4                   # no re-eval of step 4
    sc2.apply_note(None)                         # missing note: no-op
    assert sc2.policy.last_up == 4


def test_autoscaler_sampler_rides_the_snapshot():
    h = _Harness(ranks=(0, 1, 2))
    sc = Autoscaler(h, lambda s: 0.0)
    sc.mode = "brownout"
    row = metrics.snapshot()["samplers"]["serve_autoscale_by_class"]
    assert row["world"] == 3.0
    assert row["mode"] == float(sauto.MODES.index("brownout"))
    assert row["mode_name"] == "brownout"        # JSON-only string
    for k in ("shed_bulk", "shed_normal", "queue_depth",
              "oldest_wait_us"):
        assert isinstance(row[k], float), k


# ----------------------------------------- admission gate under resize
def test_admission_gate_queues_across_a_resize_window(no_failures):
    """The PR 15 gate contract under an autoscaler resize: a step
    arriving while the window is open queues (depth + oldest-age
    telemetry live), then drains onto the NEW communicator once the
    resize installs it — no collective ever tears across the
    membership change."""
    from ompi_tpu.ft import recovery as _recovery

    old = _FakeComm(ranks=(0, 1), name="fake-old")
    new = _FakeComm(ranks=(0, 1, 2), name="fake-grown")
    gate = AdmissionGate(old)
    queued0 = pv["serve_queued_steps"].value
    got = {}
    _recovery._recovering[0] += 1
    try:
        t = threading.Thread(target=lambda: got.update(
            comm=gate.admit()))
        t.start()
        deadline = time.monotonic() + 10.0
        while gate.queue_depth() == 0 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert gate.queue_depth() == 1
        time.sleep(0.005)
        assert gate.oldest_wait_us() > 0.0
        gauges = {g["name"]: g["value"]
                  for g in metrics.snapshot()["gauges"]}
        assert gauges["serve_admission_queue_depth"] == 1.0
        assert gauges["serve_admission_oldest_wait_us"] > 0.0
        # the resize lands: new world installed, THEN the window closes
        gate.install(new)
        gate.full_size = new.Get_size()
    finally:
        _recovery._recovering[0] -= 1
    t.join(timeout=30)
    assert not t.is_alive()
    assert got["comm"] is new                    # re-admitted onto M=3
    assert pv["serve_queued_steps"].value == queued0 + 1
    assert gate.queue_depth() == 0
    gauges = {g["name"]: g["value"]
              for g in metrics.snapshot()["gauges"]}
    assert gauges["serve_admission_queue_depth"] == 0.0


# -------------------------------------------------- prometheus grammar
def test_promexport_grammar_over_the_autoscale_surface(no_failures):
    """The new gauges, the by-class sampler (with its JSON-only string
    field), the demand EWMA and the RTO histogram must all render as
    valid Prometheus exposition text."""
    h = _Harness(ranks=(0, 1, 2))
    sc = Autoscaler(h, lambda s: 2.0)
    h.gate._publish_queue()
    metrics.ewma_update("serve_autoscale_demand", 2.0)
    metrics.gauge_set("serve_autoscale_world", 3.0)
    sc.rto.start("arrival")
    sc.rto.stop("arrival")
    text = metrics.render_prometheus()
    assert promexport.validate(text) == []
    assert "serve_admission_queue_depth" in text
    assert "serve_autoscale_world" in text
    assert 'serve_autoscale_rto_us_bucket' in text
    assert "mode_name" not in text               # strings are JSON-only


# ------------------------------------------------------- mpitop cells
def test_mpitop_world_cell_sampler_and_fallback():
    snap = {"samplers": {"serve_autoscale_by_class":
                         {"world": 3.0, "mode_name": "armed"}}}
    assert mpitop.world_cell(snap) == "3"
    snap["samplers"]["serve_autoscale_by_class"]["mode_name"] = \
        "scaling"
    assert mpitop.world_cell(snap) == "3~"
    snap["samplers"]["serve_autoscale_by_class"]["mode_name"] = \
        "brownout"
    assert mpitop.world_cell(snap) == "3!"
    # pvar/gauge fallback (snapshot written before the sampler existed)
    snap = {"pvars": {"serve_autoscale_decisions": 5},
            "gauges": [{"name": "serve_autoscale_world", "labels": {},
                        "value": 2.0}]}
    assert mpitop.world_cell(snap) == "2"
    assert mpitop.world_cell({"pvars": {}}) == ""   # never attached


def test_mpitop_shed_cell_sampler_and_fallback():
    snap = {"samplers": {"serve_autoscale_by_class":
                         {"shed_bulk": 4.0, "shed_normal": 2.0}}}
    assert mpitop.shed_cell(snap) == "4b/2n"
    snap = {"pvars": {"serve_shed_steps_bulk": 1,
                      "serve_shed_steps_normal": 0}}
    assert mpitop.shed_cell(snap) == "1b/0n"
    assert mpitop.shed_cell({"pvars": {}}) == ""
    snap = {"samplers": {"serve_autoscale_by_class":
                         {"shed_bulk": 0.0, "shed_normal": 0.0}}}
    assert mpitop.shed_cell(snap) == ""          # nothing ever shed


# ------------------------------------------------------- registration
def test_autoscale_cvars_and_pvars_registered():
    vars_ = all_vars()
    for name in ("serve_autoscale_eval_steps",
                 "serve_autoscale_min_world",
                 "serve_autoscale_max_world",
                 "serve_autoscale_up_util",
                 "serve_autoscale_down_util",
                 "serve_autoscale_up_cooldown_steps",
                 "serve_autoscale_down_cooldown_steps",
                 "serve_autoscale_max_step",
                 "serve_autoscale_queue_high",
                 "serve_autoscale_headroom_min",
                 "serve_autoscale_rearm_evals",
                 "serve_autoscale_rto_budget_ms",
                 "dpm_spawn_retries", "dpm_spawn_retry_backoff_ms"):
        assert name in vars_, name
    for name in ("serve_autoscale_decisions", "serve_autoscale_scale_ups",
                 "serve_autoscale_scale_downs",
                 "serve_autoscale_brownouts", "serve_shed_steps_bulk",
                 "serve_shed_steps_normal"):
        assert name in pv, name


def test_info_cli_lists_autoscale_surface(capsys):
    from ompi_tpu.tools.info import main as info_main

    info_main(["--level", "9", "--param", "serve", "--pvars"])
    out = capsys.readouterr().out
    assert "serve_autoscale_max_world" in out
    assert "serve_autoscale_rto_budget_ms" in out
    assert "serve_shed_steps_bulk" in out


# ----------------------------------------------------------- procmode
def test_autoscale_scenario_procmode(tmp_path):
    """The ISSUE 20 acceptance proof: closed-form traffic drives
    grow -> steady -> flash-crowd brownout -> shrink in ONE run, the
    world size decided by the controller, state bitwise-exact after
    every resize, RTO per trigger class from the metrics plane, zero
    steady-state SLO violations, LATENCY p99 inside its pre-spike band
    while BULK/NORMAL shed."""
    dumps = str(tmp_path / "dumps")
    os.makedirs(dumps, exist_ok=True)
    try:
        r = run_mpi(
            2, os.path.join("tests", "procmode", "check_autoscale.py"),
            "scenario", timeout=220,
            # a 1s SLO: 'zero violations in steady state' must hold
            # under tier-1 parallel load, not just on an idle host
            mca=FT_SERVE + (("serve_slo_us", "1000000.0"),),
            env_extra=(("OMPI_TPU_MCA_metrics_dir", dumps),))
    except subprocess.TimeoutExpired:
        raise AssertionError(
            "autoscale scenario hung; blame:\n" + _blame(dumps))
    out = r.stdout
    assert r.returncode == 0, out + r.stderr + _blame(dumps)
    # 2 origin ranks + 1 grown newcomer run the shared tail; the
    # newcomer retires at the shrink, so only 2 ranks reach OK
    assert out.count("AUTOSCALE-GROW") == 3, out
    assert out.count("AUTOSCALE-STEADY") == 3, out
    assert out.count("AUTOSCALE-BROWNOUT") == 3, out
    assert out.count("AUTOSCALE-SHRINK") == 2, out
    assert out.count("AUTOSCALE-LAT") == 2, out
    assert out.count("AUTOSCALE-OK") == 2, out
    assert re.search(r"AUTOSCALE-GROW rank \d world=3", out)
    assert re.search(r"AUTOSCALE-SHRINK rank \d world=2", out)
    assert re.search(r"AUTOSCALE-STEADY rank \d .*violations=0", out)
    assert re.search(r"shed_bulk=[1-9]", out)
    assert re.search(r"shed_normal=[1-9]", out)
    # the newcomer joins mid-stream (its GROW line reads rto=joined)
    # and is deterministically the shrink victim, so OK is origin-only
    assert "rto=joined" in out
    assert out.count("src=origin") == 2 and "src=grown" not in out


def test_spawn_retry_procmode():
    """dpm.spawn transient-failure retry: a child that dies before
    wireup is retried on a bounded backoff budget (satellite 1); a
    persistent failure still raises ERR_SPAWN once the budget burns."""
    r = run_mpi(
        1, os.path.join("tests", "procmode", "check_spawn_retry.py"),
        "parent", timeout=180, mca=(("coll_sm_enable", "0"),))
    out = r.stdout
    assert r.returncode == 0, out + r.stderr
    assert "SPAWN-RETRY-RECOVERED rank 0 retried=1" in out
    assert "SPAWN-RETRY-CHILD-OK" in out
    assert "SPAWN-RETRY-EXHAUSTED rank 0 retried=1" in out
    assert "SPAWN-RETRY-OK rank 0" in out
