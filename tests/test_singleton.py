"""Singleton (1-rank, no launcher) MPI semantics — reference:
the is_singleton path of ompi_mpi_init.c:451 and coll/self."""

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu import COMM_WORLD, COMM_SELF
from ompi_tpu.core.status import Status


def test_world_shape():
    assert COMM_WORLD.Get_size() == 1
    assert COMM_WORLD.Get_rank() == 0
    assert ompi_tpu.Is_initialized()


def test_send_recv_self():
    send = np.arange(8, dtype=np.float32)
    recv = np.zeros(8, dtype=np.float32)
    req = COMM_WORLD.Irecv(recv, source=0, tag=7)
    COMM_WORLD.Send(send, dest=0, tag=7)
    st = Status()
    req.Wait(st)
    np.testing.assert_array_equal(send, recv)
    assert st.Get_source() == 0 and st.Get_tag() == 7
    assert st.Get_count(ompi_tpu.FLOAT32) == 8


def test_unexpected_then_recv():
    send = np.array([3.5], dtype=np.float64)
    COMM_WORLD.Send(send, dest=0, tag=11)
    recv = np.zeros(1, dtype=np.float64)
    COMM_WORLD.Recv(recv, source=ompi_tpu.ANY_SOURCE, tag=ompi_tpu.ANY_TAG)
    assert recv[0] == 3.5


def test_probe_iprobe():
    assert not COMM_WORLD.Iprobe(tag=99)
    COMM_WORLD.Send(np.zeros(2, np.int32), dest=0, tag=99)
    st = Status()
    assert COMM_WORLD.Iprobe(tag=99, status=st)
    assert st.Get_count(ompi_tpu.INT32) == 2
    recv = np.zeros(2, np.int32)
    COMM_WORLD.Recv(recv, tag=99)


def test_sendrecv():
    send = np.array([1, 2], np.int64)
    recv = np.zeros(2, np.int64)
    COMM_WORLD.Sendrecv(send, dest=0, sendtag=5, recvbuf=recv,
                        source=0, recvtag=5)
    np.testing.assert_array_equal(recv, send)


def test_collectives_singleton():
    a = np.arange(4, dtype=np.float32)
    out = np.zeros(4, dtype=np.float32)
    COMM_WORLD.Allreduce(a, out)
    np.testing.assert_array_equal(out, a)
    COMM_WORLD.Bcast(a, root=0)
    out2 = np.zeros(4, dtype=np.float32)
    COMM_WORLD.Allgather(a, out2)
    np.testing.assert_array_equal(out2, a)
    COMM_WORLD.Barrier()


def test_comm_self():
    assert COMM_SELF.Get_size() == 1
    b = np.array([9], np.int32)
    COMM_SELF.Send(b, dest=0, tag=1)
    r = np.zeros(1, np.int32)
    COMM_SELF.Recv(r, tag=1)
    assert r[0] == 9


def test_split_dup_singleton():
    c = COMM_WORLD.Split(color=0, key=0)
    assert c.Get_size() == 1
    d = COMM_WORLD.Dup()
    assert d.Get_size() == 1
    assert d.cid != COMM_WORLD.cid


def test_mprobe_mrecv():
    COMM_WORLD.Send(np.array([42], np.int32), dest=0, tag=13)
    st = Status()
    msg = COMM_WORLD.Mprobe(tag=13, status=st)
    r = np.zeros(1, np.int32)
    COMM_WORLD.Mrecv(r, msg)
    assert r[0] == 42


def test_persistent_requests():
    send = np.array([7.0], np.float32)
    recv = np.zeros(1, np.float32)
    sreq = COMM_WORLD.Send_init(send, dest=0, tag=21)
    rreq = COMM_WORLD.Recv_init(recv, source=0, tag=21)
    for i in range(3):
        send[0] = i
        rreq.Start()
        sreq.Start()
        sreq.Wait()
        rreq.Wait()
        assert recv[0] == i


def test_datatype_send_recv_derived():
    from ompi_tpu.core.datatype import FLOAT32

    t = FLOAT32.Create_vector(2, 2, 3).Commit()
    src = np.arange(6, dtype=np.float32)
    dst = np.zeros(6, dtype=np.float32)
    COMM_WORLD.Send([src, 1, t], dest=0, tag=31)
    COMM_WORLD.Recv([dst, 1, t], source=0, tag=31)
    np.testing.assert_array_equal(dst, [0, 1, 0, 3, 4, 0])
